"""SQLite event store backend.

Replaces the reference's HBase event store
(`/root/reference/data/src/main/scala/io/prediction/data/storage/hbase/`)
for single-host deployments: one SQLite file per storage source, one table
per (app, channel) — mirroring the reference's table-per-app/channel layout
(`HBEventsUtil.scala:51-57`).  The HBase row-key design
(md5(entity) ++ time ++ uuid, `HBEventsUtil.scala:74-129`) exists to make
entity-scoped time-range scans cheap; the SQLite equivalents are the
composite indexes below.  WAL mode + a per-store write lock give concurrent
reader / single-writer semantics adequate for the event server.

The batch read path (:meth:`SQLiteEventStore.find_columnar`) bypasses Event
object construction and reads straight into NumPy arrays — the `PEvents`
analogue (`HBPEvents.scala:66-199`), where the reference instead parallel-scans
HBase regions into RDDs.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import json
import logging
import os
import re
import sqlite3
import threading
from pathlib import Path
from typing import Iterator, Optional, Sequence

import numpy as np

from ..resilience.policy import check_deadline
from ._sqlite_util import SerializedConnection
from .columnar import EventFrame
from .event import (
    DataMap,
    Event,
    from_millis,
    new_event_id,
    new_event_ids,
    time_millis,
    validate_event,
)
from .levents import NO_TARGET, EventStore, TargetFilter

__all__ = ["SQLiteEventStore", "SCHEMA_VERSION", "event_to_row"]


def event_to_row(event: Event, eid: str) -> tuple:
    """The 11-column storage row for an event — the schema every raw-row
    path speaks (`insert_raw_rows`, the native importer, the ingest
    WAL's logged payloads).  Module-level so the event server can frame
    rows for `storage.wal` without holding a store reference."""
    return (
        eid,
        event.event,
        event.entity_type,
        event.entity_id,
        event.target_entity_type,
        event.target_entity_id,
        json.dumps(event.properties.to_json(), separators=(",", ":")),
        time_millis(event.event_time),
        json.dumps(list(event.tags)),
        event.pr_id,
        time_millis(event.creation_time),
    )

logger = logging.getLogger(__name__)

# Versioned schema + forward migrations — the capability the reference
# ships as 0.8.x->0.9 HBase upgrade tooling
# (`data/.../storage/hbase/upgrade/Upgrade.scala`): a schema change must
# not strand existing event DBs (VERDICT r4 #7).  The version is stamped
# in the SQLite header (``PRAGMA user_version``); opening a store runs
# every migration from the DB's stamped version up to SCHEMA_VERSION in
# one transaction, and refuses (loudly) a DB stamped NEWER than this
# framework understands instead of corrupting it.
#
# v0 = pre-versioning DBs (rounds before stamping existed): same column
#      layout, but index/aux-table presence varied — the 0->1 migration
#      makes all of them certain.
# v1 = current: 11-column events tables, 3 composite indexes,
#      _scan_versions aux table, header stamped.
SCHEMA_VERSION = 1


# the per-table secondary indexes, ONE definition: table schema, the
# 0->1 migration, and the bulk-import defer/rebuild (names AND create
# statements) all derive from this — adding a 4th index here updates
# every consumer at once
_INDEXES = (
    ("time", "event_time"),
    ("entity", "entity_type, entity_id, event_time"),
    ("name", "event, event_time"),
)
_INDEX_SQL = tuple(
    f"CREATE INDEX IF NOT EXISTS {{t}}_{sfx} ON {{t}} ({cols})"
    for sfx, cols in _INDEXES
)
_INDEX_NAMES = tuple(f"{{t}}_{sfx}" for sfx, _ in _INDEXES)


def _migrate_0_to_1(conn: sqlite3.Connection) -> None:
    """Bring a pre-versioning DB to v1: ensure the aux table and every
    per-table index exists for each events table already in the file.
    Purely additive — legacy rows are untouched and stay readable."""
    tables = [
        r[0] for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name LIKE 'events\\_%' ESCAPE '\\'"
        )
    ]
    conn.execute(
        "CREATE TABLE IF NOT EXISTS _scan_versions "
        "(tbl TEXT PRIMARY KEY, v INTEGER NOT NULL)"
    )
    for t in tables:
        for stmt in _INDEX_SQL:
            conn.execute(stmt.format(t=t))


# version -> migration to version+1; future schema changes append here
_MIGRATIONS = {0: _migrate_0_to_1}

_SCHEMA = """
CREATE TABLE IF NOT EXISTS {table} (
  event_id TEXT PRIMARY KEY,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL,
  event_time INTEGER NOT NULL,
  tags TEXT NOT NULL,
  pr_id TEXT,
  creation_time INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS _scan_versions (
  tbl TEXT PRIMARY KEY,
  v INTEGER NOT NULL
);
""" + "".join(
    # index DDL derived from _INDEX_SQL so fresh tables, the 0->1
    # migration, and the bulk defer/rebuild can never disagree
    s.replace("{t}", "{table}") + ";\n" for s in _INDEX_SQL
)


def _table_name(app_id: int, channel_id: int) -> str:
    # mirrors events_<appId>[_<channelId>] (HBEventsUtil.scala:51-57)
    return f"events_{app_id}" if channel_id == 0 else f"events_{app_id}_{channel_id}"


class SQLiteEventStore(EventStore):
    def __init__(self, path: str | Path = ":memory:",
                 lock_name: Optional[str] = None):
        if not isinstance(path, (str, Path)):
            # str(dict) would silently become a garbage FILENAME
            raise TypeError(
                f"path must be str/Path, got {type(path).__name__} "
                "(pass conf['path'], not the conf dict)"
            )
        self._path = str(path)
        # pio-scope opt-in (``lock_name``): the sharded store names
        # each shard's writer lock so per-shard contention books under
        # pio_lock_wait_seconds{lock="store_shard_<i>"}; the default
        # single-file store keeps a plain RLock (zero added cost for
        # the thousands of short-lived stores tests build)
        if lock_name is not None:
            from ..obs.scope import TimedLock

            self._lock = TimedLock(lock_name, reentrant=True)
        else:
            self._lock = threading.RLock()
        self._local = threading.local()
        self._known_tables: set[str] = set()
        # :memory: must share one connection across threads; wrap it so
        # interleaved multi-thread statements serialize under the lock
        # (file-backed stores use per-thread connections instead)
        self._shared = self._path == ":memory:"
        if self._shared:
            self._conn_shared = SerializedConnection(
                self._connect(), self._lock
            )
        else:
            # touch eagerly: schema-version stamping/migration (and the
            # newer-than-framework refusal) must happen at OPEN, not on
            # whichever thread's first query happens to connect
            self._conn

    def _connect(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self._path, check_same_thread=False)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        # without a busy timeout sqlite raises SQLITE_BUSY *immediately*
        # on any cross-connection contention (e.g. a WAL checkpoint racing
        # a commit), which surfaced as rare 500s under the event server's
        # concurrent posts; waiting is always the right call here
        conn.execute("PRAGMA busy_timeout=10000")
        self._ensure_schema_version(conn)
        return conn

    def _ensure_schema_version(self, conn: sqlite3.Connection) -> None:
        """Stamp/migrate the DB to SCHEMA_VERSION on open (idempotent;
        later connections of the same file see the stamp and return on
        the first check).  Concurrency: BEGIN IMMEDIATE serializes two
        processes opening the same legacy file — the version is
        re-read inside the write transaction, so the loser re-checks
        and finds the winner's stamp."""
        v = conn.execute("PRAGMA user_version").fetchone()[0]
        if v == SCHEMA_VERSION:
            return
        if v > SCHEMA_VERSION:
            raise RuntimeError(
                f"event DB {self._path!r} has schema v{v}, newer than "
                f"this framework's v{SCHEMA_VERSION} — refusing to "
                "open (upgrade predictionio_tpu instead)"
            )
        with self._lock:
            conn.execute("BEGIN IMMEDIATE")
            try:
                # re-read under the write lock: another process may have
                # migrated (or a NEWER framework stamped) while we
                # waited — never overwrite a stamp >= ours, and refuse
                # a newer one here too or the loser would DOWNGRADE it
                v = conn.execute("PRAGMA user_version").fetchone()[0]
                if v >= SCHEMA_VERSION:
                    conn.rollback()
                    if v > SCHEMA_VERSION:
                        raise RuntimeError(
                            f"event DB {self._path!r} has schema v{v}, "
                            f"newer than this framework's "
                            f"v{SCHEMA_VERSION} — refusing to open "
                            "(upgrade predictionio_tpu instead)"
                        )
                    return
                while v < SCHEMA_VERSION:
                    mig = _MIGRATIONS.get(v)
                    if mig is None:
                        raise RuntimeError(
                            f"no migration path from event-DB schema "
                            f"v{v} to v{SCHEMA_VERSION}"
                        )
                    mig(conn)
                    v += 1
                conn.execute(f"PRAGMA user_version = {SCHEMA_VERSION}")
                conn.commit()
            except BaseException:
                conn.rollback()
                raise

    def schema_version(self) -> int:
        """The opened DB's stamped schema version (== SCHEMA_VERSION
        after a successful open)."""
        return int(
            self._conn.execute("PRAGMA user_version").fetchone()[0]
        )

    @property
    def _conn(self) -> "sqlite3.Connection | SerializedConnection":
        if self._shared:
            return self._conn_shared
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._connect()
            self._local.conn = conn
        return conn

    def _ensure_table(self, app_id: int, channel_id: int) -> str:
        t = _table_name(app_id, channel_id)
        if t not in self._known_tables:
            with self._lock:
                self._conn.executescript(_SCHEMA.format(table=t))
                self._conn.commit()
                self._known_tables.add(t)
        return t

    def _bump_version(self, t: str) -> None:
        """Monotonic per-table write counter, bumped INSIDE each write's
        transaction — the scan cache's change fingerprint.  (count,
        max rowid) alone is not change-proof: sqlite reuses the max rowid
        after its row is deleted, so a delete+insert pair could leave it
        unchanged and serve a stale snapshot.  A rolled-back bulk scope
        rolls its bump back too, keeping the counter consistent with the
        visible data.
        """
        self._conn.execute(
            "INSERT INTO _scan_versions VALUES (?, 1) "
            "ON CONFLICT(tbl) DO UPDATE SET v = v + 1",
            (t,),
        )

    def _version(self, t: str) -> int:
        row = self._conn.execute(
            "SELECT v FROM _scan_versions WHERE tbl=?", (t,)
        ).fetchone()
        return int(row[0]) if row else 0

    # -- lifecycle --------------------------------------------------------
    def init_channel(self, app_id: int, channel_id: int = 0) -> bool:
        self._ensure_table(app_id, channel_id)
        return True

    def remove_channel(self, app_id: int, channel_id: int = 0) -> bool:
        t = _table_name(app_id, channel_id)
        with self._lock:
            self._conn.execute(f"DROP TABLE IF EXISTS {t}")
            # the version table may not exist yet on a store that never
            # ensured any event table; removal must still bump (cached
            # scans of the dropped table die with it)
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS _scan_versions "
                "(tbl TEXT PRIMARY KEY, v INTEGER NOT NULL)"
            )
            self._bump_version(t)
            self._conn.commit()
            self._known_tables.discard(t)
        return True

    def close(self) -> None:
        with self._lock:
            if self._shared:
                self._conn_shared.close()
            else:
                conn = getattr(self._local, "conn", None)
                if conn is not None:
                    conn.close()
                    self._local.conn = None

    def compact(self) -> None:
        """VACUUM + WAL truncate: rebuild the DB without the pages
        deletes freed (`app trim` leaves them allocated) and fold the
        rewrite back into the main file — in WAL mode VACUUM's result
        lives in the -wal until a checkpoint, so without TRUNCATE the
        on-disk footprint would not shrink at all.  Must run outside
        any transaction and takes the writer lock for its duration —
        an offline-maintenance operation, not a serving-path one."""
        with self._lock:
            conn = self._conn
            conn.commit()  # VACUUM refuses inside a transaction
            conn.execute("VACUUM")
            conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            conn.commit()

    # -- writes -----------------------------------------------------------
    def _row(self, event: Event, eid: str) -> tuple:
        return event_to_row(event, eid)

    def insert(self, event: Event, app_id: int, channel_id: int = 0,
               validate: bool = True) -> str:
        # the storage boundary honors a caller's propagated time budget
        # (resilience/policy.Deadline): no-op unless a scope is active
        check_deadline("event store write")
        if validate:
            validate_event(event)
        t = self._ensure_table(app_id, channel_id)
        eid = event.event_id or new_event_id()
        with self._lock:
            self._conn.execute(
                f"INSERT OR REPLACE INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                self._row(event, eid),
            )
            self._bump_version(t)
            if not self._bulk_depth:
                self._conn.commit()
        return eid

    def insert_batch(
        self, events, app_id: int, channel_id: int = 0,
        validate: bool = True,
    ) -> list[str]:
        t = self._ensure_table(app_id, channel_id)
        events = list(events)
        fresh = iter(new_event_ids(len(events)))
        rows, ids = [], []
        for e in events:
            if validate:
                validate_event(e)
            eid = e.event_id or next(fresh)
            ids.append(eid)
            rows.append(self._row(e, eid))
        with self._lock:
            if self._bulk_depth:
                self._maybe_defer_indexes(t)
            self._conn.executemany(
                f"INSERT OR REPLACE INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?)", rows
            )
            self._bump_version(t)
            if not self._bulk_depth:
                self._conn.commit()
        return ids

    def insert_raw_rows(self, rows, app_id: int, channel_id: int = 0) -> None:
        """Low-level bulk insert of pre-built storage rows.

        The native importer fast path (`tools/import_export.py` +
        `native/jsonl_scan.cpp`) extracts row fields without constructing
        Event objects; each row must match the 11-column events schema of
        :meth:`_row` exactly and be pre-validated.  Not part of the
        EventStore contract — callers feature-test with ``hasattr``.
        """
        t = self._ensure_table(app_id, channel_id)
        with self._lock:
            if self._bulk_depth:
                self._maybe_defer_indexes(t)
            self._conn.executemany(
                f"INSERT OR REPLACE INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                rows,
            )
            self._bump_version(t)
            if not self._bulk_depth:
                self._conn.commit()

    def purge_older_than(self, cutoff_millis: int, app_id: int,
                         channel_id: int = 0) -> int:
        """TTL enforcement for the live ingest window: delete rows whose
        EVENT time predates ``cutoff_millis`` and return the count.

        Event time, not creation time — the window the trending
        re-scans and fold-in deltas reason in.  Watermark cursors stay
        valid: a purge below the cursor is invisible to the scan, and a
        cursor below the purge floor simply finds fewer rows — stale
        events it would have folded in are gone, which is the TTL's
        contract.  (sqlite only ever reuses a freed MAX rowid, and only
        when the newest-INSERTED row carries the oldest EVENT time —
        live ingest never does that; bulk historical imports should
        purge before cursors are cut.)  Not part of the EventStore ABC
        — callers feature-test with ``hasattr``.
        """
        t = self._ensure_table(app_id, channel_id)
        with self._lock:
            cur = self._conn.execute(
                f"DELETE FROM {t} WHERE event_time < ?",
                (int(cutoff_millis),),
            )
            n = cur.rowcount if cur.rowcount and cur.rowcount > 0 else 0
            if n:
                self._bump_version(t)
            if not self._bulk_depth:
                self._conn.commit()
        return n

    def iter_raw_rows(self, app_id: int, channel_id: int = 0):
        """Yield raw 11-column storage rows (schema of :meth:`_row`).

        The exporter fast path: composing wire JSON straight from stored
        parts skips Event construction + re-serialization.  Not part of
        the EventStore contract — callers feature-test with ``hasattr``.
        """
        t = self._ensure_table(app_id, channel_id)
        # same ordering as find(): exports stay time-sorted
        cur = self._conn.execute(
            f"SELECT * FROM {t} ORDER BY event_time, event_id"
        )
        while True:
            rows = cur.fetchmany(10_000)
            if not rows:
                return
            yield from rows

    @property
    def _bulk_depth(self) -> int:
        return getattr(self._local, "bulk_depth", 0)

    # bulk writes into a table at or below this row count drop the
    # secondary indexes and rebuild once at commit; above it, the table
    # is big enough that a full rebuild would cost more than the
    # incremental maintenance of a (presumed small) append
    _DEFER_MAX_EXISTING_ROWS = 100_000

    def _maybe_defer_indexes(self, t: str) -> None:
        """Called under the lock from bulk-scope write paths: drop the
        table's secondary indexes for the duration of the scope when
        the table is small (fresh imports — the certified 20M path —
        have zero existing rows).  Big tables keep their indexes: a
        10k-event append to a 20M-row table must not trigger a full
        three-index rebuild at commit."""
        if not getattr(self._local, "bulk_defer", True):
            return
        if t in self._local.bulk_dropped or t in self._local.bulk_kept:
            return
        # existence probe at O(threshold), NOT COUNT(*): a full count
        # scans the whole table — worst exactly on the big tables this
        # check protects
        big = self._conn.execute(
            f"SELECT 1 FROM {t} LIMIT 1 OFFSET {self._DEFER_MAX_EXISTING_ROWS}"
        ).fetchone()
        if big:
            self._local.bulk_kept.add(t)
            return
        # python sqlite3 implicitly BEGINs only for DML, not DDL — the
        # drops must join the scope's transaction or a rollback would
        # restore the rows but leave the indexes gone
        conn = self._conn
        raw = getattr(conn, "_conn", conn)  # SerializedConnection proxy
        if not raw.in_transaction:
            conn.execute("BEGIN")
        for name in _INDEX_NAMES:
            conn.execute(f"DROP INDEX IF EXISTS {name.format(t=t)}")
        self._local.bulk_dropped.add(t)

    @contextlib.contextmanager
    def bulk(self, defer_indexes: bool = True):
        """Defer commits to the end of the scope: bulk imports pay one
        fsync instead of one per 5k-event batch.

        Scoped to the CALLING THREAD: connections are thread-local, so a
        store-wide flag would make a concurrent writer on another thread
        skip the commit its own connection needs (rows stuck invisible in
        an open transaction).  Other threads' writes keep their normal
        commit-per-call behavior while a bulk scope is active here.

        A failed scope ROLLS BACK instead of committing: the single
        transaction makes a crashed import atomic — no half-persisted
        file with no marker of how far it got.  Every write path on this
        thread (insert/insert_batch/delete/delete_batch) defers its
        commit inside the scope.  Caveats: creating a NEW (app, channel)
        table mid-scope runs DDL, which sqlite auto-commits — call
        ``init_channel`` before the scope for strict atomicity (the bulk
        importer does); and the shared-connection ``:memory:`` mode can
        have another thread's commit absorb pending rows (test-only
        backend, single-writer assumption).

        Index deferral (``defer_indexes=True``, the importer default):
        the first bulk write to a SMALL table (see
        ``_maybe_defer_indexes``) drops its secondary indexes inside
        the open transaction and rebuilds them wholesale just before
        the commit — incremental B-tree maintenance on random entity
        keys was 62% of import wall time at ML-20M scale (profiled;
        BENCH_FULLSCALE_CPU.json import stage), while a post-load
        rebuild is one sort per index.  A rollback restores the
        indexes with everything else (sqlite DDL is transactional).
        Pass ``defer_indexes=False`` for SHORT atomicity scopes (e.g.
        the sharded store wrapping one request's groups): rebuilding
        whole-table indexes per 50-event request would be quadratic
        steady-state ingest.  The flag is consulted only when THIS
        call opens the outermost scope; nested scopes inherit it.
        """
        self._local.bulk_depth = self._bulk_depth + 1
        if self._local.bulk_depth == 1:
            self._local.bulk_dropped = set()
            self._local.bulk_kept = set()
            self._local.bulk_defer = defer_indexes
        try:
            yield self
        except BaseException:
            self._local.bulk_depth -= 1
            if self._local.bulk_depth == 0:
                with self._lock:
                    self._conn.rollback()
                    # normally the rollback restores the dropped
                    # indexes, but interleaved DDL (_ensure_table for a
                    # NEW app/channel) implicitly COMMITs mid-scope,
                    # making the drop durable — rebuild idempotently
                    # (IF NOT EXISTS: a no-op when rollback sufficed)
                    # so a failed import can't strand an index-less
                    # table across restarts
                    self._rebuild_dropped_indexes()
                    self._conn.commit()
            raise
        else:
            self._local.bulk_depth -= 1
            if self._local.bulk_depth == 0:
                with self._lock:
                    self._rebuild_dropped_indexes()
                    self._conn.commit()

    def _rebuild_dropped_indexes(self) -> None:
        """Recreate (IF NOT EXISTS) the secondary indexes of every
        table this thread's bulk scope dropped; called under the
        lock."""
        for t in self._local.bulk_dropped:
            # a remove_channel inside the scope may have dropped the
            # table out from under its indexes
            if not self._conn.execute(
                "SELECT 1 FROM sqlite_master "
                "WHERE type='table' AND name=?", (t,)
            ).fetchone():
                continue
            for stmt in _INDEX_SQL:
                self._conn.execute(stmt.format(t=t))
        self._local.bulk_dropped = set()

    # -- point reads ------------------------------------------------------
    @staticmethod
    def _event_from_row(r: tuple) -> Event:
        return Event(
            event_id=r[0],
            event=r[1],
            entity_type=r[2],
            entity_id=r[3],
            target_entity_type=r[4],
            target_entity_id=r[5],
            properties=DataMap(json.loads(r[6])),
            event_time=from_millis(r[7]),
            tags=tuple(json.loads(r[8])),
            pr_id=r[9],
            creation_time=from_millis(r[10]),
        )

    def get(self, event_id: str, app_id: int, channel_id: int = 0) -> Optional[Event]:
        t = self._ensure_table(app_id, channel_id)
        cur = self._conn.execute(f"SELECT * FROM {t} WHERE event_id=?", (event_id,))
        row = cur.fetchone()
        return self._event_from_row(row) if row else None

    def delete(self, event_id: str, app_id: int, channel_id: int = 0) -> bool:
        t = self._ensure_table(app_id, channel_id)
        with self._lock:
            cur = self._conn.execute(
                f"DELETE FROM {t} WHERE event_id=?", (event_id,)
            )
            self._bump_version(t)
            if not self._bulk_depth:
                self._conn.commit()
            return cur.rowcount > 0

    def delete_batch(self, event_ids, app_id: int, channel_id: int = 0) -> int:
        t = self._ensure_table(app_id, channel_id)
        ids = [(eid,) for eid in event_ids]
        if not ids:
            return 0
        with self._lock:
            cur = self._conn.executemany(
                f"DELETE FROM {t} WHERE event_id=?", ids
            )
            removed = cur.rowcount if cur.rowcount >= 0 else len(ids)
            # a no-op delete must not invalidate cached scans (sharded
            # stores fan every id to every shard; only the shard that
            # actually held rows has a changed table)
            if removed:
                self._bump_version(t)
            if not self._bulk_depth:
                self._conn.commit()
            return removed

    # -- scans ------------------------------------------------------------
    def _query(
        self,
        table: str,
        start_time,
        until_time,
        entity_type,
        entity_id,
        event_names,
        target_entity_type: TargetFilter,
        target_entity_id: TargetFilter,
        limit,
        reversed: bool,
        columns: str = "*",
    ) -> tuple[str, list]:
        where, params = [], []
        if start_time is not None:
            where.append("event_time >= ?")
            params.append(time_millis(start_time))
        if until_time is not None:
            where.append("event_time < ?")
            params.append(time_millis(until_time))
        if entity_type is not None:
            where.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            where.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            qs = ",".join("?" * len(event_names))
            where.append(f"event IN ({qs})")
            params.extend(event_names)
        for col, filt in (
            ("target_entity_type", target_entity_type),
            ("target_entity_id", target_entity_id),
        ):
            if filt is None:
                continue
            if filt is NO_TARGET:
                where.append(f"{col} IS NULL")
            else:
                where.append(f"{col} = ?")
                params.append(filt)
        sql = f"SELECT {columns} FROM {table}"
        if where:
            sql += " WHERE " + " AND ".join(where)
        sql += f" ORDER BY event_time {'DESC' if reversed else 'ASC'}, event_id"
        if limit is not None and limit >= 0:
            sql += " LIMIT ?"
            params.append(limit)
        return sql, params

    def find(
        self,
        app_id: int,
        channel_id: int = 0,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: TargetFilter = None,
        target_entity_id: TargetFilter = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        check_deadline("event store scan")
        t = self._ensure_table(app_id, channel_id)
        sql, params = self._query(
            t, start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id, limit, reversed,
        )
        cur = self._conn.execute(sql, params)
        return (self._event_from_row(r) for r in iter(cur.fetchone, None))

    # -- fused training read (scan + encode in C) -------------------------
    def find_ratings(
        self,
        app_id: int,
        channel_id: int = 0,
        event_names: Sequence[str] = ("rate",),
        rating_property: Optional[str] = "rating",
        dedup: str = "last",
        entity_type: Optional[str] = None,
        cache: Optional[bool] = None,
    ):
        """COO :class:`~predictionio_tpu.storage.columnar.Ratings`
        straight from the events table in ONE native pass — the
        training-read hot path fused (scan + string-id dictionary
        build), replacing find_columnar + to_ratings' ~145 s + ~19 s at
        ML-20M scale with a single C loop over the sqlite B-tree
        (`native/sqlite_scan.cpp`).  ``rating_property=None`` is the
        implicit-feedback read (every event counts 1.0 — the
        similarproduct/ecommerce view-events path).  Falls back to
        exactly ``find_columnar(minimal=True) -> to_ratings`` when the
        native lib is absent, the db is in-memory, or the scan errors
        (non-strict JSON in properties makes json_extract raise).

        Encoding matches ``to_ratings``' sorted-unique determinism:
        the native first-seen codes are remapped through one argsort of
        the (small) unique-id table.  Dedup shares ``dedup_coo`` with
        the python path.
        """
        from . import scan_cache
        from .columnar import Ratings, dedup_coo
        from ..storage.bimap import StringIndex

        event_names = list(event_names)
        # same snapshot cache as find_columnar (same correctness story:
        # key embeds the table write-version + db identity), but at the
        # RATINGS level — repeat trains/sweeps skip the whole scan AND
        # the encode, not just the cursor walk
        cache_key = None
        v_before = None
        if (
            scan_cache.enabled(cache)
            and self._path != ":memory:"
            and self._bulk_depth == 0
        ):
            t0 = self._ensure_table(app_id, channel_id)
            st = os.stat(self._path)
            v_before = self._version(t0)
            cache_key = scan_cache.key(
                self._path, t0,
                (v_before, st.st_ino, st.st_ctime_ns),
                ["find_ratings", event_names, rating_property, dedup,
                 entity_type],
            )
            cached = scan_cache.load_ratings(cache_key)
            if cached is not None:
                self.last_ratings_scan_path = "cache"
                return cached

        simple = rating_property is None or bool(
            re.fullmatch(r"[A-Za-z0-9_]+", rating_property)
        )
        native = None
        if (
            simple and event_names
            and self._path != ":memory:" and self._bulk_depth == 0
        ):
            from ..native import scan_ratings_sqlite

            t = self._ensure_table(app_id, channel_id)
            # same WHERE semantics as the fallback's _query: event
            # names and entity_type are VALUES (bound); the table name
            # and the validated property name are identifiers
            value_sql = (
                f", json_extract(properties, '$.{rating_property}')"
                if rating_property is not None else ""
            )
            qs = ",".join("?" * len(event_names))
            sql = (
                f"SELECT entity_id, target_entity_id, event_time"
                f"{value_sql} FROM {t} WHERE event IN ({qs})"
            )
            binds = list(event_names)
            if entity_type is not None:
                sql += f" AND entity_type = ?{len(binds) + 1}"
                binds.append(entity_type)
            try:
                native = scan_ratings_sqlite(
                    self._path, sql, binds,
                    has_value_col=rating_property is not None,
                )
            except RuntimeError as e:
                logger.warning(
                    "native ratings scan fell back to python: %s", e
                )
        if native is None:
            # recorded so benchmarks can label which path actually ran
            # (a "fused" stage that silently fell back would compare a
            # mislabeled slow path against the fused claims)
            self.last_ratings_scan_path = "python"
            # cache=False: the result is cached at the RATINGS level
            # below; a frame snapshot would never be read back and
            # would only crowd the shared LRU
            frame = self.find_columnar(
                app_id, channel_id, event_names=event_names,
                float_property=rating_property, minimal=True,
                entity_type=entity_type, cache=False,
            )
            out = frame.to_ratings(
                rating_property=rating_property, dedup=dedup
            )
            return self._maybe_store_ratings(
                out, cache_key, v_before, app_id, channel_id
            )
        self.last_ratings_scan_path = "native"

        u, i, v, t_ms, user_ids, item_ids = native
        # first-seen -> sorted-unique codes (to_ratings determinism)
        uo = np.argsort(user_ids)
        io = np.argsort(item_ids)
        urank = np.empty(len(uo), np.int32)
        urank[uo] = np.arange(len(uo), dtype=np.int32)
        irank = np.empty(len(io), np.int32)
        irank[io] = np.arange(len(io), dtype=np.int32)
        u = urank[u] if len(u) else u
        i = irank[i] if len(i) else i
        ok = ~np.isnan(v)
        u, i, v, t_ms = u[ok], i[ok], v[ok], t_ms[ok]
        u, i, v = dedup_coo(u, i, v, t_ms, len(item_ids), dedup)
        out = Ratings(
            user_ix=u.astype(np.int32),
            item_ix=i.astype(np.int32),
            rating=v.astype(np.float32),
            users=StringIndex(user_ids[uo]),
            items=StringIndex(item_ids[io]),
        )
        return self._maybe_store_ratings(
            out, cache_key, v_before, app_id, channel_id
        )

    def _maybe_store_ratings(self, out, cache_key, v_before, app_id,
                             channel_id):
        """ONE store gate for both find_ratings branches: snapshot only
        when the table is provably unchanged across the scan (same rule
        as find_columnar's frame snapshots)."""
        from . import scan_cache

        if (
            cache_key is not None
            and self._version(self._ensure_table(app_id, channel_id))
            == v_before
        ):
            scan_cache.store_ratings(cache_key, out)
        return out

    # -- incremental scans (pio-live watermark cursor) --------------------
    def max_rowid(self, app_id: int, channel_id: int = 0) -> int:
        """Largest rowid of the (app, channel) table (0 when empty): the
        event store's high-water mark.  ``MAX(rowid)`` is answered off
        the table B-tree root, not a scan."""
        t = self._ensure_table(app_id, channel_id)
        row = self._conn.execute(f"SELECT MAX(rowid) FROM {t}").fetchone()
        return int(row[0]) if row and row[0] is not None else 0

    def high_water_cursor(self, app_id: int, channel_id: int = 0) -> int:
        """The cursor at the current high-water mark (same shape the
        sharded store exposes; here a cursor IS a rowid)."""
        return self.max_rowid(app_id, channel_id)

    def cursor_lag(self, app_id: int, channel_id: int = 0,
                   cursor: int = 0) -> int:
        """Rows written past ``cursor`` — the freshness debt the
        watermark gauges report (the sharded store sums per shard)."""
        return max(self.max_rowid(app_id, channel_id) - int(cursor), 0)

    def find_rows_since(
        self,
        app_id: int,
        channel_id: int = 0,
        cursor: int = 0,
        limit: Optional[int] = None,
        event_names: Optional[Sequence[str]] = None,
        newest_first: bool = False,
    ) -> tuple[list[tuple], int]:
        """Raw rows written after a rowid watermark, in insertion order.

        Returns ``(rows, new_cursor)`` where each row is ``(rowid,
        <the 11 storage columns of _row>)`` with ``rowid > cursor``,
        rowid-ascending, and ``new_cursor`` is the largest rowid
        returned (== ``cursor`` when nothing is new).  The rowid is the
        table's B-tree key, so this is an INDEXED range scan — the
        incremental primitive the pio-live fold-in watermark and the
        dashboard's recent-events view share, instead of re-scanning
        the whole table per poll.

        Semantics callers rely on:

        * rowids are assigned monotonically by sqlite while the table's
          max row is never deleted; ``INSERT OR REPLACE`` of an
          existing event_id assigns a FRESH rowid, so updated events
          re-enter the scan window (a fold-in wants exactly that).
        * ``limit`` bounds one page; advancing ``cursor`` to the
          returned ``new_cursor`` and calling again pages through a
          backlog without skipping or repeating rows.
        * ``newest_first=True`` reverses the order (dashboard view);
          the cursor contract is unchanged (``new_cursor`` is still the
          max rowid seen).
        """
        t = self._ensure_table(app_id, channel_id)
        where = ["rowid > ?"]
        params: list = [int(cursor)]
        if event_names is not None:
            qs = ",".join("?" * len(event_names))
            where.append(f"event IN ({qs})")
            params.extend(event_names)
        sql = (
            f"SELECT rowid, * FROM {t} WHERE {' AND '.join(where)} "
            f"ORDER BY rowid {'DESC' if newest_first else 'ASC'}"
        )
        if limit is not None and limit >= 0:
            sql += " LIMIT ?"
            params.append(limit)
        rows = self._conn.execute(sql, params).fetchall()
        new_cursor = int(cursor)
        if rows:
            new_cursor = max(int(r[0]) for r in rows)
        return rows, new_cursor

    def find_since(
        self,
        app_id: int,
        channel_id: int = 0,
        cursor: int = 0,
        limit: Optional[int] = None,
        event_names: Optional[Sequence[str]] = None,
        newest_first: bool = False,
    ) -> tuple[list[tuple[int, Event]], int]:
        """:meth:`find_rows_since` decoded to ``(rowid, Event)`` pairs."""
        rows, new_cursor = self.find_rows_since(
            app_id, channel_id, cursor, limit, event_names, newest_first
        )
        return (
            [(int(r[0]), self._event_from_row(r[1:])) for r in rows],
            new_cursor,
        )

    # -- columnar batch read (PEvents analogue) ---------------------------
    def find_columnar(
        self,
        app_id: int,
        channel_id: int = 0,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: TargetFilter = None,
        target_entity_id: TargetFilter = None,
        float_property: Optional[str] = None,
        float_default: float = np.nan,
        minimal: bool = False,
        cache: Optional[bool] = None,
    ) -> EventFrame:
        """Bulk scan straight into column arrays.

        When ``float_property`` is given, that property is extracted per
        event into a float64 column (missing -> ``float_default``) by
        sqlite's built-in JSON1 ``json_extract`` — no per-row Python JSON
        parsing.  ``minimal=True`` additionally narrows the SELECT to the
        columns the rating/training hot path consumes (entity_id,
        target_entity_id, event_time, value): at ML-20M scale the scan
        cost is Python-object creation in the sqlite cursor, so 3 columns
        instead of 7 is ~2x (the other EventFrame fields come back
        ``None``; ``to_ratings``/``select`` handle that).

        ``cache`` (default: env ``PIO_TPU_SCAN_CACHE=1``) snapshots the
        result to an npz keyed by the table's write-version counter (see
        :meth:`_bump_version`) plus the database file's identity, so
        repeat trains on an unchanged table read back at numpy speed
        instead of re-paying the cursor scan (scan_cache.py).
        """
        t = self._ensure_table(app_id, channel_id)
        from . import scan_cache

        cache_key = None
        v_before = None
        # no caching inside a bulk() scope: uncommitted rows must never be
        # published, and a rollback would strand the snapshot
        if (
            scan_cache.enabled(cache)
            and self._path != ":memory:"
            and self._bulk_depth == 0
        ):
            st = os.stat(self._path)
            v_before = self._version(t)
            cache_key = scan_cache.key(
                self._path, t,
                # db-file identity: deleting and recreating the database
                # resets the version counter, so the inode/ctime must be
                # part of the fingerprint or the old file's snapshots
                # would be served for the new file's data
                (v_before, st.st_ino, st.st_ctime_ns),
                [
                    str(start_time), str(until_time), entity_type,
                    entity_id, event_names, target_entity_type,
                    target_entity_id, float_property, float_default,
                    minimal,
                ],
            )
            cached = scan_cache.load(cache_key)
            if cached is not None:
                return cached
        # json_extract path syntax can't express arbitrary key names
        # safely; only simple names take the SQL fast path.  NOTE: rows
        # whose properties blob holds NaN/Infinity tokens (json.dumps
        # emits them; strict JSON forbids them) make json_extract raise —
        # _scan_columns retries those scans with extract_in_sql=False.
        simple_prop = bool(
            float_property is not None
            and re.fullmatch(r"[A-Za-z0-9_]+", float_property)
        )
        try:
            cols_t, n = self._scan_columns(
                t, minimal, float_property, simple_prop,
                (start_time, until_time, entity_type, entity_id,
                 event_names, target_entity_type, target_entity_id),
            )
            extracted = simple_prop
        except sqlite3.OperationalError as e:
            if not simple_prop or "JSON" not in str(e).upper():
                raise
            cols_t, n = self._scan_columns(
                t, minimal, float_property, False,
                (start_time, until_time, entity_type, entity_id,
                 event_names, target_entity_type, target_entity_id),
            )
            extracted = False

        def obj(col):
            a = np.empty(n, dtype=object)
            if n:
                a[:] = col
            return a

        def i64(col):
            return (np.asarray(col, dtype=np.int64) if n
                    else np.empty(0, np.int64))

        def floats(col):
            # col holds json_extract results: numbers or None
            out = np.full(n, float_default, dtype=np.float64)
            for i, v in enumerate(col):
                if v is not None:
                    out[i] = float(v)
            return out

        def peek(col):
            # col holds raw properties blobs: python-side JSON peek
            out = np.full(n, float_default, dtype=np.float64)
            for i, blob in enumerate(col):
                if blob != "{}":
                    v = json.loads(blob).get(float_property)
                    if v is not None:
                        out[i] = float(v)
            return out

        values = props = None
        if float_property is not None:
            vcol = cols_t[-1]           # value/properties is always last
            values = floats(vcol) if extracted else peek(vcol)
        elif not minimal:
            props = obj([json.loads(b) for b in cols_t[-1]])

        if minimal:
            frame = EventFrame(
                event=None,
                entity_type=None,
                entity_id=obj(cols_t[0]),
                target_entity_type=None,
                target_entity_id=obj(cols_t[1]),
                event_time_ms=i64(cols_t[2]),
                properties=None,
                value=values,
            )
        else:
            frame = EventFrame(
                event=obj(cols_t[0]),
                entity_type=obj(cols_t[1]),
                entity_id=obj(cols_t[2]),
                target_entity_type=obj(cols_t[3]),
                target_entity_id=obj(cols_t[4]),
                event_time_ms=i64(cols_t[5]),
                properties=props,
                value=values,
            )
        if cache_key is not None and self._version(t) == v_before:
            # store only when no write landed during the scan: the
            # fingerprint then provably describes the snapshot's contents
            scan_cache.store(cache_key, frame)
        return frame

    def _scan_columns(self, t, minimal, float_property, extract_in_sql,
                      filters):
        """Run the columnar SELECT; returns (columns, n).

        The SELECT is built as a list so positions are structural, and the
        value/properties expression — when present — is always LAST.
        """
        (start_time, until_time, entity_type, entity_id, event_names,
         target_entity_type, target_entity_id) = filters
        sel = (
            ["entity_id", "target_entity_id", "event_time"] if minimal
            else ["event", "entity_type", "entity_id",
                  "target_entity_type", "target_entity_id", "event_time"]
        )
        if float_property is not None:
            sel.append("json_extract(properties, ?)" if extract_in_sql
                       else "properties")
        elif not minimal:
            sel.append("properties")
        sql, params = self._query(
            t, start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id, None, False,
            columns=", ".join(sel),
        )
        if extract_in_sql:
            # SELECT placeholders precede WHERE placeholders positionally
            params = [f'$."{float_property}"'] + list(params)
        rows = self._conn.execute(sql, params).fetchall()
        cols_t = list(zip(*rows)) if rows else [()] * len(sel)
        return cols_t, len(rows)
