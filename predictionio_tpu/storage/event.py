"""Event data model.

TPU-native re-expression of the reference event model
(`/root/reference/data/src/main/scala/io/prediction/data/storage/Event.scala:37-115`,
`DataMap.scala:38-202`, `PropertyMap.scala:33-96`).  Pure host code: frozen
dataclasses + a schemaless property bag.  Times are timezone-aware UTC
``datetime`` objects; wire format is ISO8601 (reference:
`DateTimeJson4sSupport.scala`).
"""

from __future__ import annotations

import datetime as _dt
import uuid
from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Mapping, Optional, Sequence

UTC = _dt.timezone.utc

__all__ = [
    "UTC",
    "DataMap",
    "PropertyMap",
    "Event",
    "EventValidationError",
    "validate_event",
    "SPECIAL_EVENTS",
    "now_utc",
    "parse_time",
    "format_time",
]


def now_utc() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


def parse_time(s: str) -> _dt.datetime:
    """Parse ISO8601 (accepts trailing 'Z')."""
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    t = _dt.datetime.fromisoformat(s)
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return t.astimezone(UTC)


def format_time(t: _dt.datetime) -> str:
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return t.astimezone(UTC).isoformat(timespec="milliseconds").replace("+00:00", "Z")


def time_millis(t: _dt.datetime) -> int:
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return int(t.timestamp() * 1000)


def from_millis(ms: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(ms / 1000.0, tz=UTC)


class EventValidationError(ValueError):
    """Raised when an event violates the validation rules
    (reference `Event.scala:70-99`)."""


class DataMapError(KeyError):
    """Raised when a required property is missing or has the wrong type."""


_MISSING = object()


class DataMap(Mapping[str, Any]):
    """Schemaless immutable property bag: name -> JSON value.

    Behavioral parity with reference `DataMap.scala:38-202`: typed ``get``
    (raises on missing / null), ``get_opt``, ``get_or_else``, merge (``++``
    -> :meth:`merged`) and key removal (``--`` -> :meth:`without`).
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        object.__setattr__(self, "_fields", dict(fields or {}))

    # -- Mapping interface ------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self):
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key) -> bool:
        return key in self._fields

    # -- typed accessors --------------------------------------------------
    def require(self, name: str) -> None:
        if name not in self._fields:
            raise DataMapError(f"The field {name} is required.")

    def get(self, name: str, default: Any = _MISSING) -> Any:
        """Return the field value; raise :class:`DataMapError` when missing
        and no default given (parity with reference ``get[T]``)."""
        if name not in self._fields or self._fields[name] is None:
            if default is _MISSING:
                raise DataMapError(f"The field {name} is required.")
            return default
        return self._fields[name]

    def get_opt(self, name: str) -> Optional[Any]:
        return self._fields.get(name)

    def get_or_else(self, name: str, default: Any) -> Any:
        v = self._fields.get(name)
        return default if v is None else v

    def get_float(self, name: str) -> float:
        return float(self.get(name))

    def get_int(self, name: str) -> int:
        return int(self.get(name))

    def get_string(self, name: str) -> str:
        return str(self.get(name))

    def get_string_list(self, name: str) -> list[str]:
        v = self.get(name)
        if not isinstance(v, list):
            raise DataMapError(f"The field {name} is not a list.")
        return [str(x) for x in v]

    # -- functional updates ----------------------------------------------
    def merged(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """``this ++ that`` — that's values win (reference `DataMap.scala`)."""
        d = dict(self._fields)
        d.update(dict(other))
        return DataMap(d)

    def without(self, keys: Iterable[str]) -> "DataMap":
        """``this -- keys``."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    @property
    def fields(self) -> dict[str, Any]:
        return dict(self._fields)

    def is_empty(self) -> bool:
        return not self._fields

    def keyset(self) -> set[str]:
        return set(self._fields)

    def to_json(self) -> dict[str, Any]:
        return dict(self._fields)

    def __eq__(self, other) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self):
        return hash(tuple(sorted((k, repr(v)) for k, v in self._fields.items())))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"


class PropertyMap(DataMap):
    """Aggregated entity property snapshot + first/last update times
    (reference `PropertyMap.scala:33-96`)."""

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]],
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        object.__setattr__(self, "first_updated", first_updated)
        object.__setattr__(self, "last_updated", last_updated)

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self._fields!r}, first={self.first_updated}, "
            f"last={self.last_updated})"
        )

    def __eq__(self, other) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self._fields == other._fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    def __hash__(self):
        return hash((super().__hash__(), self.first_updated, self.last_updated))


@dataclass(frozen=True)
class Event:
    """One behavioral event (reference `Event.scala:37-55`).

    ``target_entity_type``/``target_entity_id`` must be set together;
    ``pr_id`` links a feedback event back to a prediction.
    """

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: _dt.datetime = field(default_factory=now_utc)
    tags: Sequence[str] = ()
    pr_id: Optional[str] = None
    event_id: Optional[str] = None
    creation_time: _dt.datetime = field(default_factory=now_utc)

    def __post_init__(self) -> None:
        # ergonomics: accept a plain dict for properties (the reference's
        # typed DataMap has no such ambiguity; in Python a raw dict is the
        # natural thing to pass and must not crash later in validation)
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))

    def with_id(self, event_id: str) -> "Event":
        return replace(self, event_id=event_id)

    def to_json(self) -> dict[str, Any]:
        """API wire format (reference `EventJson4sSupport.scala:25-178`)."""
        d: dict[str, Any] = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "properties": self.properties.to_json(),
            "eventTime": format_time(self.event_time),
        }
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        d["creationTime"] = format_time(self.creation_time)
        return d

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "Event":
        """Parse the API wire format; raises on missing required fields."""
        if not isinstance(d, Mapping):
            raise EventValidationError(
                f"event must be a JSON object, got {type(d).__name__}"
            )
        try:
            name = d["event"]
            etype = d["entityType"]
            eid = d["entityId"]
        except KeyError as e:
            raise EventValidationError(f"field {e.args[0]} is required") from e
        ev = Event(
            event=str(name),
            entity_type=str(etype),
            entity_id=str(eid),
            target_entity_type=d.get("targetEntityType"),
            target_entity_id=d.get("targetEntityId"),
            properties=DataMap(d.get("properties") or {}),
            event_time=(
                parse_time(d["eventTime"]) if d.get("eventTime") else now_utc()
            ),
            tags=tuple(d.get("tags") or ()),
            pr_id=d.get("prId"),
            event_id=d.get("eventId"),
            creation_time=(
                parse_time(d["creationTime"]) if d.get("creationTime") else now_utc()
            ),
        )
        validate_event(ev)
        return ev


# --- validation (reference `Event.scala:57-115`) -------------------------

SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
BUILTIN_PROPERTIES: frozenset[str] = frozenset()


def _is_reserved_prefix(name: str) -> bool:
    return name.startswith("$") or name.startswith("pio_")


def validate_event(e: Event) -> None:
    def need(cond: bool, msg: str) -> None:
        if not cond:
            raise EventValidationError(msg)

    need(bool(e.event), "event must not be empty.")
    need(bool(e.entity_type), "entityType must not be empty string.")
    need(bool(e.entity_id), "entityId must not be empty string.")
    need(e.target_entity_type != "", "targetEntityType must not be empty string")
    need(e.target_entity_id != "", "targetEntityId must not be empty string.")
    need(
        (e.target_entity_type is None) == (e.target_entity_id is None),
        "targetEntityType and targetEntityId must be specified together.",
    )
    need(
        not (e.event == "$unset" and e.properties.is_empty()),
        "properties cannot be empty for $unset event",
    )
    need(
        not _is_reserved_prefix(e.event) or e.event in SPECIAL_EVENTS,
        f"{e.event} is not a supported reserved event name.",
    )
    need(
        e.event not in SPECIAL_EVENTS or e.target_entity_type is None,
        f"Reserved event {e.event} cannot have targetEntity",
    )
    need(
        not _is_reserved_prefix(e.entity_type)
        or e.entity_type in BUILTIN_ENTITY_TYPES,
        f"The entityType {e.entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    need(
        e.target_entity_type is None
        or not _is_reserved_prefix(e.target_entity_type)
        or e.target_entity_type in BUILTIN_ENTITY_TYPES,
        f"The targetEntityType {e.target_entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    for k in e.properties.keyset():
        need(
            not _is_reserved_prefix(k) or k in BUILTIN_PROPERTIES,
            f"The property {k} is not allowed. 'pio_' is a reserved name prefix.",
        )


def new_event_id() -> str:
    return uuid.uuid4().hex


def new_event_ids(n: int) -> list[str]:
    """``n`` unique event ids for bulk inserts: one random 64-bit prefix +
    counter — same 32-hex shape as :func:`new_event_id`, ~10x cheaper than
    ``n`` uuid4 calls (measured in the ML-20M import profile)."""
    prefix = uuid.uuid4().hex[:16]
    return [f"{prefix}{k:016x}" for k in range(n)]
