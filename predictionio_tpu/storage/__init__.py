"""Storage layer: event data model, event stores, metadata store, id maps.

TPU-native replacement for the reference `data` module
(`/root/reference/data/src/main/scala/io/prediction/data/storage/`):
embedded SQLite/in-memory backends instead of HBase/Elasticsearch, and a
columnar batch read path (struct-of-arrays -> ``jax.Array``) instead of
Spark RDDs.
"""

from .aggregate import aggregate_properties, aggregate_properties_single
from .bimap import BiMap, EntityIdIxMap, EntityMap, StringIndex
from .columnar import EventFrame, Ratings, events_to_frame
from .event import (
    DataMap,
    Event,
    EventValidationError,
    PropertyMap,
    format_time,
    now_utc,
    parse_time,
    validate_event,
)
from .levents import NO_TARGET, EventStore, MemoryEventStore
from .metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    MetadataStore,
    Model,
)
from .file_metadata import FileMetadataStore
from .registry import Storage, StorageError, get_storage, reset_storage
from .sharded_events import ShardedSQLiteEventStore
from .sqlite_events import SQLiteEventStore
from .store import LEventStore, PEventStore, app_name_to_id

__all__ = [
    "aggregate_properties",
    "aggregate_properties_single",
    "BiMap",
    "StringIndex",
    "EntityIdIxMap",
    "EntityMap",
    "LEventStore",
    "PEventStore",
    "app_name_to_id",
    "EventFrame",
    "Ratings",
    "events_to_frame",
    "DataMap",
    "Event",
    "EventValidationError",
    "PropertyMap",
    "format_time",
    "now_utc",
    "parse_time",
    "validate_event",
    "NO_TARGET",
    "EventStore",
    "MemoryEventStore",
    "ShardedSQLiteEventStore",
    "SQLiteEventStore",
    "AccessKey",
    "App",
    "Channel",
    "EngineInstance",
    "EngineManifest",
    "EvaluationInstance",
    "MetadataStore",
    "FileMetadataStore",
    "Model",
    "Storage",
    "StorageError",
    "get_storage",
    "reset_storage",
]
