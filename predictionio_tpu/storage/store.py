"""Engine-facing store facades (the reference's L4 layer).

Parity with `data/src/main/scala/io/prediction/data/store/`:

* :func:`app_name_to_id` — `store/Common.scala` ``appNameToId``: resolves an
  app **name** (+ optional channel name) to ``(app_id, channel_id)`` via the
  metadata store, raising on unknown names.
* :class:`PEventStore` — `store/PEventStore.scala:54-114`: the batch read API
  used from DataSources.  ``find`` returns a columnar
  :class:`~predictionio_tpu.storage.columnar.EventFrame` (the TPU-native
  replacement for ``RDD[Event]``) and ``aggregate_properties`` returns folded
  entity property snapshots.
* :class:`LEventStore` — `store/LEventStore.scala:59-88`: the low-latency
  single-entity read API used from ``Algorithm.predict`` at serving time
  (e-commerce template's seen/unavailable-item filtering), with an explicit
  ``timeout``-free synchronous contract and latest-first ordering.

Both facades address data by **app name + channel name**, never raw ids —
mirroring the reference's deliberate API asymmetry with the DAO layer.
"""

from __future__ import annotations

import datetime as _dt
from typing import Iterator, Optional, Sequence

from .aggregate import PropertyMap
from .columnar import EventFrame
from .event import Event
from .registry import Storage, get_storage

__all__ = ["app_name_to_id", "PEventStore", "LEventStore"]


def app_name_to_id(
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> tuple[int, int]:
    """Resolve (app name, channel name) -> (app_id, channel_id).

    Mirrors `store/Common.scala` ``appNameToId``: unknown app or channel is
    an error; ``channel_name=None`` means the default channel (id 0).
    """
    storage = storage or get_storage()
    md = storage.get_metadata()
    app = md.app_get_by_name(app_name)
    if app is None:
        raise ValueError(f"App with name '{app_name}' does not exist")
    if channel_name is None:
        return app.id, 0
    for ch in md.channel_get_by_app(app.id):
        if ch.name == channel_name:
            return app.id, ch.id
    raise ValueError(
        f"Channel '{channel_name}' does not exist in app '{app_name}'"
    )


class PEventStore:
    """Batch (training-time) read facade addressed by app name."""

    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage or get_storage()

    def find(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type=None,
        target_entity_id=None,
    ) -> EventFrame:
        """Columnar batch read (`PEventStore.scala:54-80`)."""
        app_id, channel_id = app_name_to_id(
            app_name, channel_name, self._storage
        )
        es = self._storage.get_event_store()
        kwargs = dict(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )
        # part of the EventStore contract: the base class supplies a
        # generic implementation, sqlite overrides with a native bulk read
        return es.find_columnar(**kwargs)

    def aggregate_properties(
        self,
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> dict[str, PropertyMap]:
        """Entity property snapshots (`PEventStore.scala:94-114`)."""
        app_id, channel_id = app_name_to_id(
            app_name, channel_name, self._storage
        )
        es = self._storage.get_event_store()
        return es.aggregate_properties_of(
            app_id=app_id,
            entity_type=entity_type,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )


class LEventStore:
    """Low-latency (serving-time) read facade addressed by app name."""

    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage or get_storage()

    def find_by_entity(
        self,
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type=None,
        target_entity_id=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """Entity-scoped scan, latest-first by default
        (`LEventStore.scala:59-88`)."""
        app_id, channel_id = app_name_to_id(
            app_name, channel_name, self._storage
        )
        es = self._storage.get_event_store()
        return es.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )
