"""Env-var-driven storage registry.

Parity with the reference `Storage` object
(`/root/reference/data/src/main/scala/io/prediction/data/storage/Storage.scala:40-296`):
``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ ``_PATH``) define named sources, and
``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}`` map
the three repositories onto sources.  Builtin backend types are ``sqlite``,
``memory`` and ``localfs`` (for model blobs) instead of
hbase/elasticsearch/hdfs.

Third-party EVENTDATA/METADATA backends plug in WITHOUT touching this
module: a TYPE value containing a dot is treated as a dotted import
path (``PIO_STORAGE_SOURCES_X_TYPE=mypkg.stores.RedisEventStore``) and
the named class is instantiated with the source's config dict — the
same extension point `Storage.scala:183-224` provides via classpath
reflection from the TYPE string (VERDICT r4 #6: the if/elif chains here
previously made new backends a framework edit).  MODELDATA is the
exception: its contract is a filesystem directory
(:meth:`Storage.model_data_dir`), so only path-based builtin types
apply there — custom model persistence hooks in at the algorithm level
instead (``Algorithm.save_model``/``load_model``).  When no env config
exists, everything defaults to SQLite files under ``$PIO_TPU_HOME``
(default ``~/.predictionio_tpu``).
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Optional

from .event import Event, now_utc
from .levents import EventStore, MemoryEventStore
from .metadata import MetadataStore
from .sqlite_events import SQLiteEventStore

__all__ = ["Storage", "StorageError", "get_storage", "reset_storage"]


class StorageError(RuntimeError):
    pass


def _home(env: dict[str, str]) -> Path:
    return Path(
        env.get("PIO_TPU_HOME") or os.path.expanduser("~/.predictionio_tpu")
    )


class Storage:
    """One resolved storage configuration: event store + metadata + model dir."""

    def __init__(self, env: Optional[dict[str, str]] = None):
        self.env = dict(env if env is not None else os.environ)
        self._lock = threading.Lock()
        self._event_store: Optional[EventStore] = None
        self._metadata: Optional[MetadataStore] = None

    # -- source resolution ------------------------------------------------
    def _repo_source(self, repo: str) -> tuple[str, dict[str, str]]:
        """Resolve repository -> (type, source config).  Mirrors
        `Storage.scala:45-149` (sourcesToClientMeta / repositoriesToDataObjectMeta).
        """
        name = self.env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_NAME", repo.lower())
        source = self.env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE", "")
        if source:
            stype = self.env.get(f"PIO_STORAGE_SOURCES_{source}_TYPE")
            if stype is None:
                raise StorageError(
                    f"repository {repo} points at source {source} but "
                    f"PIO_STORAGE_SOURCES_{source}_TYPE is not set"
                )
            conf = {
                k[len(f"PIO_STORAGE_SOURCES_{source}_"):].lower(): v
                for k, v in self.env.items()
                if k.startswith(f"PIO_STORAGE_SOURCES_{source}_")
            }
            # dotted TYPEs are python import paths — case-sensitive
            return (
                stype if "." in stype else stype.lower()
            ), conf
        # defaults under home: sqlite DBs, plain dir for model blobs
        home = _home(self.env)
        if repo == "MODELDATA":
            return "localfs", {"type": "localfs", "path": str(home / "models")}
        return "sqlite", {"type": "sqlite", "path": str(home / f"{name}.db")}

    # -- pluggable backends (Storage.scala:183-224) ------------------------
    @staticmethod
    def _load_custom(stype: str, conf: dict[str, str]):
        """Dotted-path TYPE -> import the class and instantiate it with
        the source's config dict (lower-cased suffix keys: ``type``,
        ``path``, anything else the operator set on the source).  The
        constructor contract for third-party backends is exactly
        ``Backend(conf)`` — the analogue of the reference's reflective
        ``getConstructors ... newInstance(client, config)``."""
        import importlib

        mod_name, _, attr = stype.rpartition(".")
        try:
            cls = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            raise StorageError(
                f"cannot load storage backend {stype!r}: {e}"
            ) from e
        try:
            return cls(conf)
        except Exception as e:  # noqa: BLE001 — config errors surface here
            raise StorageError(
                f"storage backend {stype!r} failed to initialize "
                f"with config {sorted(conf)}: {e}"
            ) from e

    # -- accessors (Storage.scala:259-290) --------------------------------
    def get_event_store(self) -> EventStore:
        with self._lock:
            if self._event_store is None:
                stype, conf = self._repo_source("EVENTDATA")
                if stype == "memory":
                    self._event_store = MemoryEventStore()
                elif stype == "sqlite":
                    path = conf.get("path", ":memory:")
                    if path != ":memory:":
                        Path(path).parent.mkdir(parents=True, exist_ok=True)
                    self._event_store = SQLiteEventStore(path)
                elif stype == "sqlite-sharded":
                    # entity-hash sharded writes (region-parallel HBase
                    # analogue); PATH is a directory, SHARDS the count
                    from .sharded_events import ShardedSQLiteEventStore

                    try:
                        self._event_store = ShardedSQLiteEventStore(
                            conf.get("path")
                            or str(_home(self.env) / "eventdata-shards"),
                            n_shards=int(conf.get("shards", "4")),
                        )
                    except ValueError as e:
                        # bad SHARDS value, count < 1, or a marker
                        # mismatch — all config-class errors; surface
                        # them the way every other registry misconfig
                        # surfaces
                        raise StorageError(
                            f"sqlite-sharded source: {e}"
                        ) from e
                elif "." in stype:
                    self._event_store = self._load_custom(stype, conf)
                else:
                    raise StorageError(f"unknown event store type: {stype}")
            return self._event_store

    def get_metadata(self) -> MetadataStore:
        with self._lock:
            if self._metadata is None:
                stype, conf = self._repo_source("METADATA")
                if stype == "memory":
                    self._metadata = MetadataStore(":memory:")
                elif stype == "sqlite":
                    path = conf.get("path", ":memory:")
                    if path != ":memory:":
                        Path(path).parent.mkdir(parents=True, exist_ok=True)
                    self._metadata = MetadataStore(path)
                elif stype == "jsonfs":
                    # JSON-document file tree (the reference's alternate
                    # mongodb metadata backend, re-designed for the
                    # shared-filesystem multi-host shape — file_metadata.py)
                    from .file_metadata import FileMetadataStore

                    path = conf.get("path") or str(
                        _home(self.env) / "metadata-json"
                    )
                    self._metadata = FileMetadataStore(path)
                elif "." in stype:
                    self._metadata = self._load_custom(stype, conf)
                else:
                    raise StorageError(f"unknown metadata store type: {stype}")
            return self._metadata

    def model_data_dir(self) -> Path:
        stype, conf = self._repo_source("MODELDATA")
        if stype in ("sqlite", "localfs", "memory"):
            p = Path(conf.get("path", str(_home(self.env) / "models")))
            if p.suffix == ".db":
                p = p.with_suffix("")
            p.mkdir(parents=True, exist_ok=True)
            return p
        raise StorageError(f"unknown model data type: {stype}")

    # -- startup self-check (Storage.scala:237-257) ------------------------
    def verify_all_data_objects(self) -> None:
        """Touch all repositories, incl. a test event write to app 0."""
        md = self.get_metadata()
        md.app_get_all()
        es = self.get_event_store()
        es.init_channel(0)
        eid = es.insert(
            Event(event="test", entity_type="test", entity_id="test",
                  event_time=now_utc()),
            app_id=0,
        )
        es.delete(eid, app_id=0)
        self.model_data_dir()

    def close(self) -> None:
        with self._lock:
            if self._event_store is not None:
                self._event_store.close()
                self._event_store = None
            if self._metadata is not None:
                self._metadata.close()
                self._metadata = None


_global: Optional[Storage] = None
_global_lock = threading.Lock()


def get_storage() -> Storage:
    global _global
    with _global_lock:
        if _global is None:
            _global = Storage()
        return _global


def reset_storage(storage: Optional[Storage] = None) -> None:
    """Swap the process-global storage (tests / embedding)."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.close()
        _global = storage
