"""Bidirectional id maps: string entity ids <-> contiguous device-friendly
integer indices.

Re-expression of reference `BiMap.scala:25-164` (``BiMap.stringInt`` /
``stringLong``) built for the TPU path: the forward map is a Python dict for
O(1) host lookups at serving time, the inverse is a NumPy object array so
batched top-k results coming back from the device can be decoded with a
single fancy-index instead of a Python loop.  Index assignment is by first
appearance when built incrementally, or sorted-unique when built from bulk
arrays (deterministic either way — SURVEY §7 hard-part 3).
"""

from __future__ import annotations

from typing import Generic, Iterable, Mapping, Sequence, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")

__all__ = ["BiMap", "StringIndex", "EntityIdIxMap", "EntityMap"]


class BiMap(Generic[K, V]):
    """Immutable bidirectional map (reference `BiMap.scala:25-110`)."""

    def __init__(self, forward: Mapping[K, V]):
        self._f = dict(forward)
        self._i = {v: k for k, v in self._f.items()}
        if len(self._i) != len(self._f):
            raise ValueError("BiMap values must be unique")

    def __getitem__(self, k: K) -> V:
        return self._f[k]

    def get(self, k: K, default=None):
        return self._f.get(k, default)

    def contains(self, k: K) -> bool:
        return k in self._f

    __contains__ = contains

    def inverse(self) -> "BiMap[V, K]":
        return BiMap(self._i)

    def inv_get(self, v: V, default=None):
        return self._i.get(v, default)

    def keys(self):
        return self._f.keys()

    def values(self):
        return self._f.values()

    def items(self):
        return self._f.items()

    def __len__(self) -> int:
        return len(self._f)

    def to_dict(self) -> dict:
        return dict(self._f)

    # -- constructors matching BiMap.stringInt/stringLong ----------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        uniq = sorted(set(keys))
        return BiMap({k: i for i, k in enumerate(uniq)})


def _pandas():
    """pandas if importable (baked into this image), else None.

    Its hash-table factorize/get_indexer run the 20M-id dictionary
    builds at C speed (SURVEY §7 hard-part 3: measured 8.4 s vs 42 s for
    the pure-dict path at ML-20M scale); every caller keeps a
    pandas-free fallback.
    """
    try:
        import pandas as pd

        return pd
    except Exception:  # pragma: no cover - image always has pandas
        return None


# below this many lookups the dict path wins (no pandas Index build)
_BULK_ENCODE_MIN = 65_536


class StringIndex:
    """Contiguous index over string ids with a vectorized decode path.

    The TPU-facing counterpart of ``BiMap.stringInt``: ``encode`` maps id
    arrays to int32 (unknowns -> -1), ``decode`` maps device index arrays
    back to ids via one NumPy gather.
    """

    __slots__ = ("_to_ix", "_ids", "_pd_index")

    def __init__(self, ids: Sequence[str]):
        arr = np.asarray(list(ids), dtype=object)
        if len(set(arr.tolist())) != len(arr):
            raise ValueError("StringIndex ids must be unique")
        self._ids = arr
        self._to_ix = {s: i for i, s in enumerate(arr.tolist())}
        self._pd_index = None

    @staticmethod
    def from_values(values: Iterable[str]) -> "StringIndex":
        """Deterministic build: sorted unique (bulk-array path)."""
        return StringIndex(sorted(set(values)))

    @staticmethod
    def factorize(values) -> tuple["StringIndex", np.ndarray]:
        """Index + int32 codes for ``values`` in one pass.

        Equivalent to ``idx = from_values(values); idx.encode(values)``
        (sorted-unique determinism) but hash-based at C speed when
        pandas is available — the training-read hot path for string id
        dictionaries at 20M-rating scale.
        """
        pd = _pandas()
        if pd is not None:
            arr = np.asarray(values, dtype=object)
            codes, uniques = pd.factorize(arr, sort=True)
            if len(arr) and (codes < 0).any():
                # pd.factorize encodes None/NaN as -1; the pandas-free
                # fallback raises on them (sorted() over mixed types) —
                # keep the loud behavior so malformed events never get
                # silently dropped
                raise TypeError("id values must be non-null strings")
            return StringIndex(uniques.tolist()), codes.astype(np.int32)
        idx = StringIndex.from_values(values)
        return idx, idx.encode(values)

    def __len__(self) -> int:
        return len(self._ids)

    def __contains__(self, s: str) -> bool:
        return s in self._to_ix

    def get(self, s: str, default: int = -1) -> int:
        return self._to_ix.get(s, default)

    def __getitem__(self, s: str) -> int:
        return self._to_ix[s]

    def id_of(self, ix: int) -> str:
        return self._ids[ix]

    @property
    def ids(self) -> np.ndarray:
        return self._ids

    def encode(self, values: Iterable[str]) -> np.ndarray:
        """ids -> int32 indices; unknown ids become -1."""
        if isinstance(values, np.ndarray) and len(values) >= _BULK_ENCODE_MIN:
            pd = _pandas()
            if pd is not None:
                # hash-join lookup at C speed; -1 for unknowns matches
                # the dict path exactly
                # getattr: instances unpickled from pre-_pd_index
                # checkpoints restore only the slots they were saved with
                if getattr(self, "_pd_index", None) is None:
                    self._pd_index = pd.Index(self._ids)
                return self._pd_index.get_indexer(
                    np.asarray(values, dtype=object)
                ).astype(np.int32)
        g = self._to_ix.get
        return np.fromiter(
            (g(v, -1) for v in values), dtype=np.int32,
        )

    def decode(self, ixs: np.ndarray) -> np.ndarray:
        """int indices -> id object array (single gather)."""
        return self._ids[np.asarray(ixs)]

    def append(self, ids: Iterable[str]) -> np.ndarray:
        """Append-only growth (pio-live fold-in): add unseen ids in
        first-appearance order; returns int32 indices for EVERY given
        id (already-present ids resolve to their existing index, so a
        replayed delta maps idempotently).

        Existing indices never change meaning — ``_ids`` only grows —
        so a reader holding a decode view stays correct for every
        index it could have seen.  The new rows are published to
        ``_ids`` BEFORE their ``_to_ix`` entries appear: a concurrent
        ``get`` either misses (pre-append behavior) or hits an id whose
        row is already decodable.  Single-writer (the fold-in daemon /
        the serving delta-apply path, which holds the server state
        lock); concurrent readers need no lock.
        """
        ids = list(ids)
        out = np.empty(len(ids), dtype=np.int32)
        fresh: list[str] = []
        fresh_ix: dict[str, int] = {}
        base = len(self._ids)
        for j, s in enumerate(ids):
            ix = self._to_ix.get(s)
            if ix is None:
                # duplicate within THIS batch: first occurrence wins
                ix = fresh_ix.get(s)
                if ix is None:
                    ix = base + len(fresh)
                    fresh_ix[s] = ix
                    fresh.append(s)
            out[j] = ix
        if fresh:
            self._ids = np.concatenate(
                [self._ids, np.asarray(fresh, dtype=object)]
            )
            for k, s in enumerate(fresh):
                self._to_ix[s] = base + k
            # the pandas lookup index is rebuilt lazily on next bulk use
            self._pd_index = None
        return out


class EntityIdIxMap:
    """Entity id <-> contiguous index map (reference `EntityMap.scala:27-60`,
    ``EntityIdIxMap``).  Thin, order-preserving wrapper over
    :class:`StringIndex` keeping the reference's method names."""

    def __init__(self, id_to_ix: BiMap[str, int] | StringIndex):
        if isinstance(id_to_ix, BiMap):
            if sorted(id_to_ix.values()) != list(range(len(id_to_ix))):
                raise ValueError(
                    "EntityIdIxMap needs contiguous indices 0..n-1"
                )
            ordered = [None] * len(id_to_ix)
            for k, v in id_to_ix.items():
                ordered[v] = k
            self._index = StringIndex(ordered)
        else:
            self._index = id_to_ix

    @staticmethod
    def from_ids(ids: Iterable[str]) -> "EntityIdIxMap":
        return EntityIdIxMap(StringIndex.from_values(ids))

    def __call__(self, entity_id: str) -> int:
        return self._index[entity_id]

    def get(self, entity_id: str, default: int = -1) -> int:
        return self._index.get(entity_id, default)

    def contains(self, entity_id: str) -> bool:
        return entity_id in self._index

    __contains__ = contains

    def inverse(self, ix: int) -> str:
        return self._index.id_of(ix)

    def __len__(self) -> int:
        return len(self._index)

    @property
    def index(self) -> StringIndex:
        return self._index


class EntityMap(Generic[V]):
    """Index map + typed per-entity payload (reference
    `EntityMap.scala:62-98`): lookup by entity id or by contiguous index."""

    def __init__(self, data: Mapping[str, V]):
        self._data = dict(data)
        self.id_to_ix = EntityIdIxMap.from_ids(self._data.keys())

    def __getitem__(self, entity_id: str) -> V:
        return self._data[entity_id]

    def get(self, entity_id: str, default=None):
        return self._data.get(entity_id, default)

    def get_by_index(self, ix: int) -> V:
        return self._data[self.id_to_ix.inverse(ix)]

    def __contains__(self, entity_id: str) -> bool:
        return entity_id in self._data

    def __len__(self) -> int:
        return len(self._data)

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()
