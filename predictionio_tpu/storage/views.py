"""Deprecated batch-view API kept for source compatibility.

Re-expression of the reference's 0.8-era view layer
(`data/src/main/scala/io/prediction/data/view/LBatchView.scala`,
`PBatchView.scala`, `DataView.scala`) which newer engines replaced with the
store facades (`store/PEventStore.scala`).  Engines written against the old
`LBatchView(appId).events.filter(...).aggregateByEntityOrdered(...)` shape
can migrate mechanically; new code should use
:mod:`predictionio_tpu.storage.store` instead.

One class serves both the reference's L (local list) and P (Spark RDD)
variants: the embedded store always yields host events, and the batch
("P") aggregation path is the same columnar fold used by the facades.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Iterable, Optional, TypeVar

from .aggregate import aggregate_properties
from .event import DataMap, Event, parse_time
from .levents import EventStore

__all__ = ["EventSeq", "BatchView", "LBatchView", "PBatchView"]

T = TypeVar("T")


def _predicate(
    start_time: Optional[Any] = None,
    until_time: Optional[Any] = None,
    entity_type: Optional[str] = None,
    event_name: Optional[str] = None,
) -> Callable[[Event], bool]:
    """Compose the ViewPredicates.* filters (`LBatchView.scala:29-65`)."""
    st = parse_time(start_time) if isinstance(start_time, str) else start_time
    ut = parse_time(until_time) if isinstance(until_time, str) else until_time

    def pred(e: Event) -> bool:
        t = e.event_time
        if st is not None and t < st:
            return False
        if ut is not None and t >= ut:
            return False
        if entity_type is not None and e.entity_type != entity_type:
            return False
        if event_name is not None and e.event != event_name:
            return False
        return True

    return pred


class EventSeq:
    """List-like event sequence with the old filter/aggregate combinators
    (`LBatchView.scala:94-131`)."""

    def __init__(self, events: Iterable[Event]):
        self.events: list[Event] = list(events)

    def __iter__(self):
        return iter(self.events)

    def __len__(self) -> int:
        return len(self.events)

    def filter(
        self,
        predicate: Optional[Callable[[Event], bool]] = None,
        *,
        start_time: Optional[Any] = None,
        until_time: Optional[Any] = None,
        entity_type: Optional[str] = None,
        event_name: Optional[str] = None,
    ) -> "EventSeq":
        pred = predicate or _predicate(
            start_time, until_time, entity_type, event_name
        )
        return EventSeq(e for e in self.events if pred(e))

    def aggregate_by_entity_ordered(
        self, init: T, op: Callable[[T, Event], T]
    ) -> dict[str, T]:
        """Per-entity time-ordered fold (`LBatchView.scala:121-131`)."""
        groups: dict[str, list[Event]] = {}
        for e in self.events:
            groups.setdefault(e.entity_id, []).append(e)
        out: dict[str, T] = {}
        for eid, evs in groups.items():
            acc = init
            for e in sorted(evs, key=lambda x: x.event_time):
                acc = op(acc, e)
            out[eid] = acc
        return out

    def group_by_entity_ordered(
        self, proc: Callable[[Event], T]
    ) -> dict[str, list[T]]:
        """Per-entity time-ordered map (`LBatchView.scala:189-200`)."""
        groups: dict[str, list[Event]] = {}
        for e in self.events:
            groups.setdefault(e.entity_id, []).append(e)
        return {
            eid: [proc(e) for e in sorted(evs, key=lambda x: x.event_time)]
            for eid, evs in groups.items()
        }


class BatchView:
    """`LBatchView`/`PBatchView` replacement over the embedded store."""

    def __init__(
        self,
        store: EventStore,
        app_id: int,
        channel_id: int = 0,
        start_time: Optional[Any] = None,
        until_time: Optional[Any] = None,
    ):
        self._store = store
        self.app_id = app_id
        self.channel_id = channel_id
        self.start_time = (
            parse_time(start_time) if isinstance(start_time, str) else start_time
        )
        self.until_time = (
            parse_time(until_time) if isinstance(until_time, str) else until_time
        )
        self._events: Optional[EventSeq] = None

    @property
    def events(self) -> EventSeq:
        """All events in the window, memoized (`LBatchView.scala:142-154`)."""
        if self._events is None:
            self._events = EventSeq(
                self._store.find(
                    self.app_id,
                    self.channel_id,
                    start_time=self.start_time,
                    until_time=self.until_time,
                )
            )
        return self._events

    def aggregate_properties(
        self, entity_type: Optional[str] = None
    ) -> dict[str, DataMap]:
        """$set/$unset/$delete snapshot per entity
        (`LBatchView.scala:156-172`, `PBatchView.scala:188-206`)."""
        evs = self.events
        if entity_type is not None:
            evs = evs.filter(entity_type=entity_type)
        return {
            eid: DataMap(pm.fields)
            for eid, pm in aggregate_properties(evs).items()
        }

    def aggregate_by_entity_ordered(
        self,
        init: T,
        op: Callable[[T, Event], T],
        predicate: Optional[Callable[[Event], bool]] = None,
    ) -> dict[str, T]:
        evs = self.events if predicate is None else self.events.filter(predicate)
        return evs.aggregate_by_entity_ordered(init, op)


def _deprecated(old: str) -> None:
    warnings.warn(
        f"{old} is the 0.8-era view API; use "
        "predictionio_tpu.storage.store facades instead",
        DeprecationWarning,
        stacklevel=3,
    )


class LBatchView(BatchView):
    def __init__(self, *a, **kw):
        _deprecated("LBatchView")
        super().__init__(*a, **kw)


class PBatchView(BatchView):
    def __init__(self, *a, **kw):
        _deprecated("PBatchView")
        super().__init__(*a, **kw)
