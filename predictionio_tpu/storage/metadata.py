"""Metadata store: apps, access keys, channels, engine manifests,
engine instances, evaluation instances, and model blobs.

Replaces the reference's Elasticsearch metadata backend
(`/root/reference/data/src/main/scala/io/prediction/data/storage/elasticsearch/`)
and the record definitions in `storage/{Apps,AccessKeys,Channels,
EngineManifests,EngineInstances,EvaluationInstances,Models}.scala` with one
embedded SQLite database.  DAO surface mirrors the reference traits; the
``ESSequences`` id generator becomes SQLite AUTOINCREMENT.

Model blobs (reference `Models.scala:30-48`: Kryo bytes keyed by engine
instance id) are stored as files next to the DB when large, rows when small —
the framework's checkpoints (orbax) reference these paths.
"""

from __future__ import annotations

import json
import secrets
import re
import sqlite3
import threading

from ._sqlite_util import SerializedConnection
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

__all__ = [
    "App",
    "AccessKey",
    "Channel",
    "EngineManifest",
    "EngineInstance",
    "EvaluationInstance",
    "Model",
    "MetadataStore",
    "CHANNEL_NAME_RE",
]

CHANNEL_NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")  # Channels.scala:27-65


def generate_access_key() -> str:
    """A fresh CLI-argument-safe access key (no leading ``-``/``_``)."""
    k = secrets.token_urlsafe(48).lstrip("-_")
    while len(k) < 24:  # extremely unlikely
        k = secrets.token_urlsafe(48).lstrip("-_")
    return k


@dataclass
class App:
    id: int
    name: str
    description: Optional[str] = None


@dataclass
class AccessKey:
    key: str
    appid: int
    events: list[str] = field(default_factory=list)  # empty = all events allowed


@dataclass
class Channel:
    id: int
    name: str
    appid: int

    @staticmethod
    def is_valid_name(s: str) -> bool:
        return bool(CHANNEL_NAME_RE.match(s))


@dataclass
class EngineManifest:
    id: str
    version: str
    name: str
    description: Optional[str] = None
    files: list[str] = field(default_factory=list)
    engine_factory: str = ""


@dataclass
class EngineInstance:
    """Full training-run record (reference `EngineInstances.scala:48-112`).

    Status lifecycle: INIT -> TRAINING -> COMPLETED (or FAILED)."""

    id: str
    status: str
    start_time: str
    end_time: str
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: dict[str, str] = field(default_factory=dict)
    mesh_conf: dict[str, Any] = field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""


@dataclass
class EvaluationInstance:
    id: str
    status: str
    start_time: str
    end_time: str
    evaluation_class: str
    engine_params_generator_class: str
    batch: str = ""
    env: dict[str, str] = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclass
class Model:
    id: str
    models: bytes


_SCHEMA = """
CREATE TABLE IF NOT EXISTS apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT
);
CREATE TABLE IF NOT EXISTS access_keys (
  key TEXT PRIMARY KEY,
  appid INTEGER NOT NULL,
  events TEXT NOT NULL DEFAULT '[]'
);
CREATE TABLE IF NOT EXISTS channels (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  appid INTEGER NOT NULL,
  UNIQUE (appid, name)
);
CREATE TABLE IF NOT EXISTS engine_manifests (
  id TEXT NOT NULL,
  version TEXT NOT NULL,
  name TEXT NOT NULL,
  description TEXT,
  files TEXT NOT NULL DEFAULT '[]',
  engine_factory TEXT NOT NULL DEFAULT '',
  PRIMARY KEY (id, version)
);
CREATE TABLE IF NOT EXISTS engine_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time TEXT NOT NULL,
  end_time TEXT NOT NULL,
  engine_id TEXT NOT NULL,
  engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL,
  engine_factory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  mesh_conf TEXT NOT NULL DEFAULT '{}',
  data_source_params TEXT NOT NULL DEFAULT '',
  preparator_params TEXT NOT NULL DEFAULT '',
  algorithms_params TEXT NOT NULL DEFAULT '',
  serving_params TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time TEXT NOT NULL,
  end_time TEXT NOT NULL,
  evaluation_class TEXT NOT NULL,
  engine_params_generator_class TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  evaluator_results TEXT NOT NULL DEFAULT '',
  evaluator_results_html TEXT NOT NULL DEFAULT '',
  evaluator_results_json TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS models (
  id TEXT PRIMARY KEY,
  models BLOB NOT NULL
);
"""


class MetadataStore:
    """All seven metadata DAOs behind one handle
    (accessor parity with `Storage.scala:259-290`)."""

    def __init__(self, path: str | Path = ":memory:"):
        if not isinstance(path, (str, Path)):
            # str(dict) would silently become a garbage FILENAME
            raise TypeError(
                f"path must be str/Path, got {type(path).__name__} "
                "(pass conf['path'], not the conf dict)"
            )
        self._path = str(path)
        self._lock = threading.RLock()
        raw = sqlite3.connect(self._path, check_same_thread=False)
        # wait out cross-PROCESS contention (multi-host chief/peer reads,
        # CLI + server sharing one metadata db) instead of SQLITE_BUSY
        raw.execute("PRAGMA busy_timeout=10000")
        # one shared connection, every statement serialized + materialized
        # under the lock: bare sqlite3 connections break under interleaved
        # multi-thread use (event-server auth reads raced training writes)
        self._conn = SerializedConnection(raw, self._lock)
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # ---------------- apps (Apps.scala) ----------------
    def app_insert(self, name: str, description: Optional[str] = None) -> App:
        with self._lock:
            cur = self._conn.execute(
                "INSERT INTO apps (name, description) VALUES (?, ?)",
                (name, description),
            )
            self._conn.commit()
            return App(id=cur.lastrowid, name=name, description=description)

    def app_get(self, app_id: int) -> Optional[App]:
        r = self._conn.execute(
            "SELECT id, name, description FROM apps WHERE id=?", (app_id,)
        ).fetchone()
        return App(*r) if r else None

    def app_get_by_name(self, name: str) -> Optional[App]:
        r = self._conn.execute(
            "SELECT id, name, description FROM apps WHERE name=?", (name,)
        ).fetchone()
        return App(*r) if r else None

    def app_get_all(self) -> list[App]:
        return [
            App(*r)
            for r in self._conn.execute(
                "SELECT id, name, description FROM apps ORDER BY id"
            )
        ]

    def app_update(self, app: App) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE apps SET name=?, description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            self._conn.commit()

    def app_delete(self, app_id: int) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM apps WHERE id=?", (app_id,))
            self._conn.commit()

    # ---------------- access keys (AccessKeys.scala) ----------------
    def access_key_insert(self, key: AccessKey) -> str:
        k = key.key or generate_access_key()
        with self._lock:
            self._conn.execute(
                "INSERT INTO access_keys (key, appid, events) VALUES (?,?,?)",
                (k, key.appid, json.dumps(key.events)),
            )
            self._conn.commit()
        return k

    def access_key_get(self, key: str) -> Optional[AccessKey]:
        r = self._conn.execute(
            "SELECT key, appid, events FROM access_keys WHERE key=?", (key,)
        ).fetchone()
        return AccessKey(r[0], r[1], json.loads(r[2])) if r else None

    def access_key_get_by_app(self, appid: int) -> list[AccessKey]:
        return [
            AccessKey(r[0], r[1], json.loads(r[2]))
            for r in self._conn.execute(
                "SELECT key, appid, events FROM access_keys WHERE appid=?", (appid,)
            )
        ]

    def access_key_get_all(self) -> list[AccessKey]:
        return [
            AccessKey(r[0], r[1], json.loads(r[2]))
            for r in self._conn.execute("SELECT key, appid, events FROM access_keys")
        ]

    def access_key_delete(self, key: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM access_keys WHERE key=?", (key,))
            self._conn.commit()

    # ---------------- channels (Channels.scala) ----------------
    def channel_insert(self, name: str, appid: int) -> Channel:
        if not Channel.is_valid_name(name):
            raise ValueError(
                f"invalid channel name {name!r}: must match {CHANNEL_NAME_RE.pattern}"
            )
        with self._lock:
            cur = self._conn.execute(
                "INSERT INTO channels (name, appid) VALUES (?,?)", (name, appid)
            )
            self._conn.commit()
            return Channel(id=cur.lastrowid, name=name, appid=appid)

    def channel_get(self, channel_id: int) -> Optional[Channel]:
        r = self._conn.execute(
            "SELECT id, name, appid FROM channels WHERE id=?", (channel_id,)
        ).fetchone()
        return Channel(*r) if r else None

    def channel_get_by_app(self, appid: int) -> list[Channel]:
        return [
            Channel(*r)
            for r in self._conn.execute(
                "SELECT id, name, appid FROM channels WHERE appid=? ORDER BY id",
                (appid,),
            )
        ]

    def channel_delete(self, channel_id: int) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM channels WHERE id=?", (channel_id,))
            self._conn.commit()

    # ---------------- engine manifests (EngineManifests.scala) ------------
    def manifest_upsert(self, m: EngineManifest) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO engine_manifests VALUES (?,?,?,?,?,?)",
                (m.id, m.version, m.name, m.description, json.dumps(m.files),
                 m.engine_factory),
            )
            self._conn.commit()

    def manifest_get(self, id: str, version: str) -> Optional[EngineManifest]:
        r = self._conn.execute(
            "SELECT * FROM engine_manifests WHERE id=? AND version=?", (id, version)
        ).fetchone()
        if not r:
            return None
        return EngineManifest(r[0], r[1], r[2], r[3], json.loads(r[4]), r[5])

    def manifest_get_all(self) -> list[EngineManifest]:
        return [
            EngineManifest(r[0], r[1], r[2], r[3], json.loads(r[4]), r[5])
            for r in self._conn.execute("SELECT * FROM engine_manifests")
        ]

    def manifest_delete(self, id: str, version: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM engine_manifests WHERE id=? AND version=?", (id, version)
            )
            self._conn.commit()

    # ---------------- engine instances (EngineInstances.scala) ------------
    _EI_COLS = (
        "id status start_time end_time engine_id engine_version engine_variant "
        "engine_factory batch env mesh_conf data_source_params preparator_params "
        "algorithms_params serving_params"
    ).split()

    def engine_instance_insert(self, ei: EngineInstance) -> str:
        with self._lock:
            self._conn.execute(
                f"INSERT OR REPLACE INTO engine_instances "
                f"VALUES ({','.join('?' * len(self._EI_COLS))})",
                (ei.id, ei.status, ei.start_time, ei.end_time, ei.engine_id,
                 ei.engine_version, ei.engine_variant, ei.engine_factory, ei.batch,
                 json.dumps(ei.env), json.dumps(ei.mesh_conf),
                 ei.data_source_params, ei.preparator_params,
                 ei.algorithms_params, ei.serving_params),
            )
            self._conn.commit()
        return ei.id

    @staticmethod
    def _ei_from_row(r) -> EngineInstance:
        return EngineInstance(
            id=r[0], status=r[1], start_time=r[2], end_time=r[3], engine_id=r[4],
            engine_version=r[5], engine_variant=r[6], engine_factory=r[7],
            batch=r[8], env=json.loads(r[9]), mesh_conf=json.loads(r[10]),
            data_source_params=r[11], preparator_params=r[12],
            algorithms_params=r[13], serving_params=r[14],
        )

    def engine_instance_get(self, id: str) -> Optional[EngineInstance]:
        r = self._conn.execute(
            "SELECT * FROM engine_instances WHERE id=?", (id,)
        ).fetchone()
        return self._ei_from_row(r) if r else None

    def engine_instance_get_all(self) -> list[EngineInstance]:
        return [
            self._ei_from_row(r)
            for r in self._conn.execute(
                "SELECT * FROM engine_instances ORDER BY start_time DESC"
            )
        ]

    def engine_instance_get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        """`getLatestCompleted` (EngineInstances.scala) — deploy picks this."""
        r = self._conn.execute(
            "SELECT * FROM engine_instances WHERE engine_id=? AND engine_version=? "
            "AND engine_variant=? AND status='COMPLETED' "
            "ORDER BY start_time DESC LIMIT 1",
            (engine_id, engine_version, engine_variant),
        ).fetchone()
        return self._ei_from_row(r) if r else None

    def engine_instance_get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        return [
            self._ei_from_row(r)
            for r in self._conn.execute(
                "SELECT * FROM engine_instances WHERE engine_id=? AND "
                "engine_version=? AND engine_variant=? AND status='COMPLETED' "
                "ORDER BY start_time DESC",
                (engine_id, engine_version, engine_variant),
            )
        ]

    def engine_instance_update(self, ei: EngineInstance) -> None:
        self.engine_instance_insert(ei)

    def engine_instance_delete(self, id: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM engine_instances WHERE id=?", (id,))
            self._conn.commit()

    # ---------------- evaluation instances --------------------------------
    def evaluation_instance_insert(self, ev: EvaluationInstance) -> str:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO evaluation_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?)",
                (ev.id, ev.status, ev.start_time, ev.end_time, ev.evaluation_class,
                 ev.engine_params_generator_class, ev.batch, json.dumps(ev.env),
                 ev.evaluator_results, ev.evaluator_results_html,
                 ev.evaluator_results_json),
            )
            self._conn.commit()
        return ev.id

    @staticmethod
    def _ev_from_row(r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0], status=r[1], start_time=r[2], end_time=r[3],
            evaluation_class=r[4], engine_params_generator_class=r[5], batch=r[6],
            env=json.loads(r[7]), evaluator_results=r[8],
            evaluator_results_html=r[9], evaluator_results_json=r[10],
        )

    def evaluation_instance_get(self, id: str) -> Optional[EvaluationInstance]:
        r = self._conn.execute(
            "SELECT * FROM evaluation_instances WHERE id=?", (id,)
        ).fetchone()
        return self._ev_from_row(r) if r else None

    def evaluation_instance_get_completed(self) -> list[EvaluationInstance]:
        return [
            self._ev_from_row(r)
            for r in self._conn.execute(
                "SELECT * FROM evaluation_instances WHERE status='EVALCOMPLETED' "
                "ORDER BY start_time DESC"
            )
        ]

    def evaluation_instance_update(self, ev: EvaluationInstance) -> None:
        self.evaluation_instance_insert(ev)

    # ---------------- model blobs (Models.scala) ---------------------------
    def model_insert(self, m: Model) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO models VALUES (?,?)", (m.id, m.models)
            )
            self._conn.commit()

    def model_get(self, id: str) -> Optional[Model]:
        r = self._conn.execute("SELECT * FROM models WHERE id=?", (id,)).fetchone()
        return Model(r[0], r[1]) if r else None

    def model_delete(self, id: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM models WHERE id=?", (id,))
            self._conn.commit()
