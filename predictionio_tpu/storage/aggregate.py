"""Entity property aggregation: folding ``$set`` / ``$unset`` / ``$delete``
event streams into per-entity property snapshots.

Behavioral parity with reference `LEventAggregator.scala:24-115` (local
iterator fold) and `PEventAggregator.scala:35-209` (the Spark
``aggregateByKey`` monoid).  Here both collapse into one host-side
implementation: the fold is over JSON property bags, which is not TPU work —
the TPU-facing output is produced downstream by
:mod:`predictionio_tpu.storage.columnar`, which turns snapshots into dense
feature arrays.

Fold semantics (per entity, events sorted by event_time ascending):
  * ``$set``    — merge properties over current (later wins); creates the
                  entity if absent.
  * ``$unset``  — remove the listed property keys (no-op if entity absent).
  * ``$delete`` — drop the entity entirely (subsequent ``$set`` recreates).
  * any other event — ignored.
Entities whose final state is "deleted"/never-set are excluded.  first/last
updated times cover every special event touching the entity (including the
trailing ``$delete``-then-``$set`` case), matching `propAggregator`.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import Iterable, Optional

from .event import DataMap, Event, PropertyMap

__all__ = ["aggregate_properties", "aggregate_properties_single"]


@dataclass
class _Prop:
    dm: Optional[DataMap] = None
    first_updated: Optional[_dt.datetime] = None
    last_updated: Optional[_dt.datetime] = None


def _fold(p: _Prop, e: Event) -> _Prop:
    if e.event == "$set":
        p.dm = e.properties if p.dm is None else p.dm.merged(e.properties)
    elif e.event == "$unset":
        p.dm = None if p.dm is None else p.dm.without(e.properties.keyset())
    elif e.event == "$delete":
        p.dm = None
    else:
        return p  # non-special events do not touch properties or times
    p.first_updated = (
        e.event_time
        if p.first_updated is None
        else min(p.first_updated, e.event_time)
    )
    p.last_updated = (
        e.event_time if p.last_updated is None else max(p.last_updated, e.event_time)
    )
    return p


def aggregate_properties(events: Iterable[Event]) -> dict[str, PropertyMap]:
    """Group by entity_id, sort by event_time, fold — returns only entities
    with defined final properties (reference `LEventAggregator.scala:24-64`)."""
    by_entity: dict[str, list[Event]] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: dict[str, PropertyMap] = {}
    for entity_id, evs in by_entity.items():
        evs.sort(key=lambda e: e.event_time)
        p = _Prop()
        for e in evs:
            p = _fold(p, e)
        if p.dm is not None:
            assert p.first_updated is not None and p.last_updated is not None
            out[entity_id] = PropertyMap(
                p.dm.fields, first_updated=p.first_updated, last_updated=p.last_updated
            )
    return out


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Fold a single entity's event stream
    (reference `LEventAggregator.scala:67-89`)."""
    evs = sorted(events, key=lambda e: e.event_time)
    p = _Prop()
    for e in evs:
        p = _fold(p, e)
    if p.dm is None:
        return None
    assert p.first_updated is not None and p.last_updated is not None
    return PropertyMap(
        p.dm.fields, first_updated=p.first_updated, last_updated=p.last_updated
    )
