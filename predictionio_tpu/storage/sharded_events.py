"""Entity-hash-sharded SQLite event store: region-parallel writes.

The reference's HBase event table is written region-parallel — its
bulk write path partitions by the md5-prefixed rowkey and each region
server commits independently
(`data/.../storage/hbase/HBPEvents.scala:180-199`, rowkey design
`HBEventsUtil.scala:74-129`).  The single-file SQLite store serializes
every write behind ONE writer lock + WAL, which caps multi-writer
ingest (~100k events/s bulk, `bench_ingest.py`; VERDICT r4 #9).  This
store shards the event table by a stable entity hash across N SQLite
files: N independent writer locks and WAL commits, so concurrent
writers (multi-core event servers, parallel importers) scale with
shard count the way region-parallel HBase writes do.

Reads compose: entity-scoped queries route to exactly one shard (the
rowkey-prefix locality property); full scans merge the per-shard
time-ordered streams (``heapq.merge``) or concatenate columnar frames
(order-independent for training: ``to_ratings`` dedups by event time,
not row position).

Routing is ``crc32(entity_type ++ entity_id) % n_shards`` — stable
across processes and runs (NOT python ``hash()``, which is salted per
process), mirroring the md5-prefix distribution of the reference's
rowkeys.  The shard count is fixed at creation and stamped in a
marker file; opening with a different count refuses loudly instead of
silently mis-routing entities.

Known semantic drift from the single-file store: re-inserting an
EXPLICIT ``event_id`` under a different entity lands in a different
shard, so the cross-file OR-REPLACE upsert cannot collapse the two rows
— both remain until deleted (``delete`` removes every copy).
Auto-generated ids are unique, so only clients that reuse ids across
entities can observe this; the reference's HBase rowkeys (entity-hash
prefixed) cannot express that operation at all.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import heapq
import json
import os
import time
import zlib
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

from ..obs import (
    STORE_SHARD_ROWS,
    STORE_SHARD_SCAN_SECONDS,
    STORE_SHARD_WRITE_SECONDS,
)
from ..resilience import faults
from .columnar import EventFrame
from .event import Event
from .levents import EventStore, ShardUnavailableError, TargetFilter
from .sqlite_events import SQLiteEventStore

__all__ = ["ShardedSQLiteEventStore"]

_MARKER = "shards.json"


def _shard_ix(entity_type: str, entity_id: str, n: int) -> int:
    h = zlib.crc32(
        f"{entity_type}\x00{entity_id}".encode("utf-8", "surrogatepass")
    )
    return h % n


class ShardedSQLiteEventStore(EventStore):
    """N SQLite event stores under one directory, routed by entity hash.

    ``path`` is a DIRECTORY (created if absent) holding
    ``shard-<i>.db`` files plus a ``shards.json`` marker recording the
    count.  Accepts the registry's source-config dict conventions via
    ``Storage`` (TYPE ``sqlite-sharded``, PATH, SHARDS).
    """

    def __init__(self, path: str | Path, n_shards: int = 4):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self._dir = Path(path)
        self._dir.mkdir(parents=True, exist_ok=True)
        marker = self._dir / _MARKER
        try:
            # atomic create: two first-time opens racing with DIFFERENT
            # shard counts must not both succeed (each would route the
            # same entity to a different file) — exactly one writes the
            # marker, the loser falls through to the compare
            with open(marker, "x") as f:
                f.write(json.dumps({"n_shards": n_shards}) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except FileExistsError:
            # the winner may still be between create and write; wait
            # for content rather than crash on an empty read
            txt = ""
            for _ in range(200):
                txt = marker.read_text()
                if txt.strip():
                    break
                time.sleep(0.01)
            else:
                raise ValueError(
                    f"shard marker {marker} exists but never gained "
                    "content (crashed concurrent creator?); remove it "
                    "to re-initialize"
                )
            stamped = json.loads(txt).get("n_shards")
            if stamped != n_shards:
                raise ValueError(
                    f"event store at {self._dir} was created with "
                    f"{stamped} shards; opening with {n_shards} would "
                    "mis-route every entity — refusing"
                )
        self.n_shards = n_shards
        # pio-scope: name each shard's writer lock so one hot shard's
        # contention is attributable on pio_lock_wait_seconds{lock=}
        self.shards = [
            SQLiteEventStore(self._dir / f"shard-{i}.db",
                             lock_name=f"store_shard_{i}")
            for i in range(n_shards)
        ]
        # pio-lens satellite: per-shard instrumentation, children
        # resolved once (labels() is too hot for the write path).  The
        # row gauge tracks THIS process's write-minus-delete delta —
        # the ingestion-skew signal ROADMAP item 3's partitioned write
        # path will be judged by, not a table count.
        self._m_write = [
            STORE_SHARD_WRITE_SECONDS.labels(shard=str(i))
            for i in range(n_shards)
        ]
        self._m_scan = [
            STORE_SHARD_SCAN_SECONDS.labels(shard=str(i))
            for i in range(n_shards)
        ]
        self._m_rows = [
            STORE_SHARD_ROWS.labels(shard=str(i))
            for i in range(n_shards)
        ]

    # pio-levee: a shard-owner worker process restricts this to its
    # fixed subset post-construction; None = every shard (the
    # single-process default).  Ownership gates WRITES only — sqlite
    # files accept cross-process READERS safely, and cursor scans must
    # see the whole keyspace regardless of who owns the writer lock.
    owned_shards: Optional[frozenset[int]] = None

    def set_owned_shards(self, shards: Optional[Iterable[int]]) -> None:
        if shards is None:
            self.owned_shards = None
            return
        owned = frozenset(int(s) for s in shards)
        bad = sorted(s for s in owned if not 0 <= s < self.n_shards)
        if bad:
            raise ValueError(
                f"owned shards {bad} out of range for "
                f"{self.n_shards}-shard store"
            )
        self.owned_shards = owned

    # -- routing ----------------------------------------------------------
    def _shard(self, entity_type: str, entity_id: str) -> SQLiteEventStore:
        return self.shards[_shard_ix(entity_type, entity_id,
                                     self.n_shards)]

    def shard_of(self, entity_type: str, entity_id: str) -> int:
        """The shard index an entity routes to — the routing table the
        ingest router and chaos tooling share with the store."""
        return _shard_ix(entity_type, entity_id, self.n_shards)

    def _check_shard_up(self, six: int) -> None:
        """``store.shard_down`` consultation (shard-scoped, see
        `resilience.faults.check_shard`); any injected error surfaces
        as the sticky `ShardUnavailableError`, never a transient."""
        try:
            faults.check_shard("store.shard_down", six)
        except ShardUnavailableError:
            raise
        except BaseException as e:
            raise ShardUnavailableError(six, str(e)) from e

    def _check_writable(self, six: int) -> None:
        if self.owned_shards is not None and six not in self.owned_shards:
            raise ShardUnavailableError(
                six,
                "shard is not owned by this worker (router misroute or "
                "stale routing table)",
            )
        self._check_shard_up(six)

    # -- lifecycle --------------------------------------------------------
    def init_channel(self, app_id: int, channel_id: int = 0) -> bool:
        for s in self.shards:
            s.init_channel(app_id, channel_id)
        return True

    def remove_channel(self, app_id: int, channel_id: int = 0) -> bool:
        ok = True
        for s in self.shards:
            ok = s.remove_channel(app_id, channel_id) and ok
        return ok

    def close(self) -> None:
        for s in self.shards:
            s.close()

    def compact(self) -> None:
        # owned-shard scoped like purge: VACUUM takes the writer lock,
        # which belongs to the owning worker in a fleet
        for i, s in enumerate(self.shards):
            if self.owned_shards is not None and i not in self.owned_shards:
                continue
            s.compact()

    # -- writes -----------------------------------------------------------
    def insert(self, event: Event, app_id: int, channel_id: int = 0,
               validate: bool = True) -> str:
        six = _shard_ix(event.entity_type, event.entity_id,
                        self.n_shards)
        self._check_writable(six)
        t0 = time.perf_counter()
        eid = self.shards[six].insert(
            event, app_id, channel_id, validate=validate
        )
        self._m_write[six].observe(time.perf_counter() - t0)
        self._m_rows[six].inc()
        return eid

    def insert_batch(
        self, events, app_id: int, channel_id: int = 0,
        validate: bool = True,
    ) -> list[str]:
        from .event import validate_event

        events = list(events)
        if validate:
            # validate EVERYTHING before any shard writes: the single
            # store's all-or-nothing semantics must survive sharding
            for e in events:
                validate_event(e)
        groups: dict[int, list[int]] = {}
        for pos, e in enumerate(events):
            groups.setdefault(
                _shard_ix(e.entity_type, e.entity_id, self.n_shards), []
            ).append(pos)
        for six in groups:
            # refuse BEFORE any shard writes: all-or-nothing semantics
            # extend to a down/foreign shard in the batch
            self._check_writable(six)
        ids: list[Optional[str]] = [None] * len(events)
        # one bulk scope spanning every touched shard: a sqlite error
        # on a later group rolls back the earlier groups too (each
        # shard's scope rolls back on the propagating exception).
        # defer_indexes=False — this scope exists for per-REQUEST
        # atomicity; whole-table index rebuilds per 50-event POST would
        # be quadratic steady-state ingest.  An importer's own
        # surrounding bulk() still defers (the outermost scope's flag
        # wins).
        with self.bulk(defer_indexes=False):
            for six, positions in groups.items():
                t0 = time.perf_counter()
                got = self.shards[six].insert_batch(
                    [events[p] for p in positions], app_id, channel_id,
                    validate=False,
                )
                self._m_write[six].observe(time.perf_counter() - t0)
                self._m_rows[six].inc(len(positions))
                for p, eid in zip(positions, got):
                    ids[p] = eid
        return ids  # aligned with the input order

    def insert_raw_rows(self, rows, app_id: int,
                        channel_id: int = 0) -> None:
        """Native-importer fast path, shard-routed: row columns 2/3 are
        entity_type/entity_id (`sqlite_events._row`)."""
        groups: dict[int, list] = {}
        for row in rows:
            groups.setdefault(
                _shard_ix(row[2], row[3], self.n_shards), []
            ).append(row)
        for six in groups:
            self._check_writable(six)
        # cross-shard atomicity as in insert_batch (and same reasoning
        # for defer_indexes=False: the importer's outer scope defers)
        with self.bulk(defer_indexes=False):
            for six, grp in groups.items():
                t0 = time.perf_counter()
                self.shards[six].insert_raw_rows(grp, app_id, channel_id)
                self._m_write[six].observe(time.perf_counter() - t0)
                self._m_rows[six].inc(len(grp))

    def purge_older_than(self, cutoff_millis: int, app_id: int,
                         channel_id: int = 0) -> int:
        """TTL fan-out (`sqlite_events.purge_older_than`): bounded live
        window across every shard this process can write.  Owned-shard
        scoped — in a worker fleet each owner trims its own files (the
        others' writer locks belong to their owners)."""
        total = 0
        for i, s in enumerate(self.shards):
            if self.owned_shards is not None and i not in self.owned_shards:
                continue
            n = s.purge_older_than(cutoff_millis, app_id, channel_id)
            if n:
                self._m_rows[i].dec(n)
            total += n
        return total

    @contextlib.contextmanager
    def bulk(self, defer_indexes: bool = True):
        with contextlib.ExitStack() as stack:
            for s in self.shards:
                stack.enter_context(s.bulk(defer_indexes=defer_indexes))
            yield self

    # -- point reads ------------------------------------------------------
    def get(self, event_id: str, app_id: int,
            channel_id: int = 0) -> Optional[Event]:
        for s in self.shards:
            ev = s.get(event_id, app_id, channel_id)
            if ev is not None:
                return ev
        return None

    def delete(self, event_id: str, app_id: int,
               channel_id: int = 0) -> bool:
        # NO short-circuit: a client that re-posted an explicit eventId
        # under a DIFFERENT entity left copies in two shards (routing is
        # by entity, so cross-shard OR-REPLACE cannot dedup them — a
        # documented semantic drift from the single store); delete must
        # remove every copy, not the first one found
        removed = [
            s.delete(event_id, app_id, channel_id) for s in self.shards
        ]
        for i, ok in enumerate(removed):
            if ok:
                self._m_rows[i].dec()
        return any(removed)

    def delete_batch(
        self, event_ids: Iterable[str], app_id: int, channel_id: int = 0
    ) -> int:
        ids = list(event_ids)
        total = 0
        for i, s in enumerate(self.shards):
            n = s.delete_batch(ids, app_id, channel_id)
            if n:
                self._m_rows[i].dec(n)
            total += n
        return total

    # -- scans ------------------------------------------------------------
    def find(
        self,
        app_id: int,
        channel_id: int = 0,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: TargetFilter = None,
        target_entity_id: TargetFilter = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        kw = dict(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, reversed=reversed,
        )
        if entity_type is not None and entity_id is not None:
            # rowkey-locality fast path: one shard holds the entity
            yield from self._shard(entity_type, entity_id).find(
                limit=limit, **kw
            )
            return
        # k-way merge of per-shard time-ordered streams; each shard is
        # given the limit too (a merged top-N needs at most N per shard)
        streams = [s.find(limit=limit, **kw) for s in self.shards]
        key = (
            (lambda e: -e.event_time.timestamp()) if reversed
            else (lambda e: e.event_time.timestamp())
        )
        merged = heapq.merge(*streams, key=key)
        if limit is None or limit < 0:
            yield from merged
            return
        import itertools

        yield from itertools.islice(merged, limit)

    def find_ratings(
        self,
        app_id: int,
        channel_id: int = 0,
        event_names=("rate",),
        rating_property="rating",
        dedup: str = "last",
        entity_type=None,
        cache=None,
    ):
        """Fused training read across shards: each shard runs its
        native scan+encode (`sqlite_events.find_ratings`), then the
        shard dictionaries merge into one global id space.

        Per-shard dedup is GLOBALLY exact here: routing is by entity,
        so every event of a (user, item) pair lives in the user's one
        shard — cross-shard duplicates of a pair cannot exist."""
        from .bimap import StringIndex
        from .columnar import Ratings

        # shards are independent files and the native scan is a
        # GIL-releasing C call: scan them CONCURRENTLY so the fused
        # read costs ~max(per-shard) on a multi-core host, not the sum
        # (the region-parallel behavior this store exists for)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(len(self.shards)) as ex:
            parts = list(ex.map(
                lambda s: s.find_ratings(
                    app_id, channel_id, event_names=event_names,
                    rating_property=rating_property, dedup=dedup,
                    entity_type=entity_type, cache=cache,
                ),
                self.shards,
            ))
        paths = {
            getattr(s, "last_ratings_scan_path", "python")
            for s in self.shards
        }
        self.last_ratings_scan_path = (
            paths.pop() if len(paths) == 1 else "mixed"
        )
        # dictionaries merge from EVERY part — a shard whose rows all
        # filtered out (e.g. propless ratings) still contributes its
        # ids, exactly like the single store's global factorize would
        users = StringIndex(sorted(set().union(
            *(p.users.ids.tolist() for p in parts)
        )))
        items = StringIndex(sorted(set().union(
            *(p.items.ids.tolist() for p in parts)
        )))
        u_out, i_out, v_out = [], [], []
        for p in parts:
            if not len(p):
                continue
            # shard-local code -> global code, one gather per side
            umap = users.encode(p.users.ids)
            imap = items.encode(p.items.ids)
            u_out.append(umap[p.user_ix])
            i_out.append(imap[p.item_ix])
            v_out.append(p.rating)
        if not u_out:
            u_out = [np.empty(0, np.int32)]
            i_out = [np.empty(0, np.int32)]
            v_out = [np.empty(0, np.float32)]
        return Ratings(
            user_ix=np.concatenate(u_out).astype(np.int32),
            item_ix=np.concatenate(i_out).astype(np.int32),
            rating=np.concatenate(v_out).astype(np.float32),
            users=users,
            items=items,
        )

    # -- incremental scans (per-shard fold-in watermarks) -----------------
    #
    # The single-file store's watermark cursor is one rowid; a sharded
    # store has N independent rowid sequences, so its cursor is a
    # VECTOR — JSON-encoded ``{"0": rowid, "1": rowid, ...}`` — carried
    # opaquely by every consumer (pio-live watermark files, delta-link
    # metadata, online-eval cursors).  Integer 0 still means "from the
    # beginning" so single-file call sites work unchanged; any other
    # integer is refused loudly (it cannot name a position in N
    # sequences).

    def _decode_cursor(self, cursor) -> list[int]:
        if isinstance(cursor, str):
            try:
                d = json.loads(cursor)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"bad shard cursor {cursor!r}: {e}"
                ) from None
            if not isinstance(d, dict):
                raise ValueError(
                    f"shard cursor must be a JSON object, got {cursor!r}"
                )
            return [int(d.get(str(i), 0)) for i in range(self.n_shards)]
        c = int(cursor or 0)
        if c == 0:
            return [0] * self.n_shards
        raise ValueError(
            f"sharded event-store cursors are JSON shard-vector "
            f"strings; a nonzero integer ({c}) cannot address "
            f"{self.n_shards} independent rowid sequences"
        )

    def _encode_cursor(self, per_shard) -> str:
        return json.dumps(
            {str(i): int(v) for i, v in enumerate(per_shard)},
            sort_keys=True, separators=(",", ":"),
        )

    # advertised capability: callers that can exploit a concurrent
    # shard scan (the trending engine's full-backlog aggregation) probe
    # this instead of sniffing types
    supports_parallel_scan = True

    def find_rows_since(
        self,
        app_id: int,
        channel_id: int = 0,
        cursor=0,
        limit: Optional[int] = None,
        event_names: Optional[Sequence[str]] = None,
        newest_first: bool = False,
        parallel: bool = False,
        tolerate_unavailable: bool = False,
    ) -> tuple[list[tuple], str]:
        """Rows written after a shard-vector watermark; returns
        ``(rows, new_cursor)`` with ``new_cursor`` the JSON-encoded
        per-shard vector (see above).  Rows are the same
        ``(rowid, <11 columns>)`` tuples the single store yields —
        NOTE the rowid is shard-LOCAL (display/debug only; the cursor
        is the paging contract, never arithmetic on row ids).

        Ordering is per-shard rowid-ascending, shards concatenated in
        index order.  Per-ENTITY ordering — the property fold-in
        correctness rests on ("last rating wins" within a window) — is
        exact, because routing pins an entity to one shard.  ``limit``
        bounds the merged page: shards are consumed in order and the
        cursor only advances for rows actually returned, so paging
        with the returned cursor walks the full backlog without
        skipping or repeating.

        ``parallel=True`` scans every shard concurrently — the
        region-parallel read analogue (ROADMAP item 3's scan half) for
        unbounded scans: N independent B-tree range scans on N
        connections instead of one serialized walk.  Results are
        concatenated in shard-index order, so the output is BITWISE the
        sequential scan's.  Ignored when ``limit`` is set (a bounded
        page consumes shards in order — scanning all of them would read
        rows the page must then discard) or when there is one shard.

        ``tolerate_unavailable=True`` is the pio-levee degradation mode
        for incremental consumers (fold-in, online eval): a shard that
        answers `ShardUnavailableError` contributes NO rows and its
        cursor COMPONENT does not advance — the vector stalls on
        exactly that shard while healthy components keep moving, so
        resuming from the returned cursor after recovery replays the
        dead shard's backlog from where it stalled, losing nothing.
        When False (default) the error propagates — one-shot readers
        must see the outage loudly, not a silently partial scan."""
        per_shard = self._decode_cursor(cursor)

        def scan_one(i, lim):
            """(rows, new_component) for shard i — stalled on outage
            when tolerated (component pinned at the input cursor)."""
            try:
                self._check_shard_up(i)
                t0 = time.perf_counter()
                rows, nc = self.shards[i].find_rows_since(
                    app_id, channel_id, cursor=per_shard[i],
                    limit=lim, event_names=event_names,
                    newest_first=newest_first,
                )
                self._m_scan[i].observe(time.perf_counter() - t0)
                return rows, int(nc)
            except ShardUnavailableError:
                if not tolerate_unavailable:
                    raise
                return [], int(per_shard[i])

        if parallel and limit is None and self.n_shards > 1:
            import concurrent.futures

            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(self.n_shards, 8),
                thread_name_prefix="shard-scan",
            ) as ex:
                results = list(ex.map(
                    lambda i: scan_one(i, None), range(self.n_shards)
                ))
            out_rows = [r for rows, _ in results for r in rows]
            return out_rows, self._encode_cursor(
                [nc for _, nc in results]
            )
        out_rows: list[tuple] = []
        new_cursor = list(per_shard)
        remaining = limit
        for i in range(self.n_shards):
            if remaining is not None and remaining <= 0:
                break
            rows, nc = scan_one(i, remaining)
            out_rows.extend(rows)
            new_cursor[i] = nc
            if remaining is not None:
                remaining -= len(rows)
        return out_rows, self._encode_cursor(new_cursor)

    def find_since(
        self,
        app_id: int,
        channel_id: int = 0,
        cursor=0,
        limit: Optional[int] = None,
        event_names: Optional[Sequence[str]] = None,
        newest_first: bool = False,
    ) -> tuple[list[tuple[int, Event]], str]:
        """:meth:`find_rows_since` decoded to ``(rowid, Event)`` pairs
        (shard-local rowids; the dashboard's recent-events view)."""
        rows, new_cursor = self.find_rows_since(
            app_id, channel_id, cursor, limit, event_names, newest_first
        )
        return (
            [(int(r[0]), SQLiteEventStore._event_from_row(r[1:]))
             for r in rows],
            new_cursor,
        )

    def max_rowid(self, app_id: int, channel_id: int = 0) -> int:
        """SUM of the per-shard high-water rowids: a scalar volume
        indicator (dashboards, coarse lag display), NOT a cursor —
        cursors are vectors (:meth:`high_water_cursor`)."""
        return sum(
            s.max_rowid(app_id, channel_id) for s in self.shards
        )

    def high_water_cursor(self, app_id: int, channel_id: int = 0) -> str:
        """The encoded shard-vector cursor at the current high-water
        mark (``foldin --from-now`` starts here)."""
        return self._encode_cursor([
            s.max_rowid(app_id, channel_id) for s in self.shards
        ])

    def cursor_lag(self, app_id: int, channel_id: int = 0,
                   cursor=0) -> int:
        """Rows written past ``cursor`` summed over shards — the
        freshness debt the watermark gauges report."""
        per_shard = self._decode_cursor(cursor)
        return sum(
            max(s.max_rowid(app_id, channel_id) - per_shard[i], 0)
            for i, s in enumerate(self.shards)
        )

    def find_columnar(
        self,
        app_id: int,
        channel_id: int = 0,
        **kw,
    ) -> EventFrame:
        """Fan out the native per-shard columnar scans, concatenate,
        and restore the contract's time ordering (one vectorized
        argsort over the merged time column — O(n log n) numpy work,
        a few seconds at 20M rows, vs the per-shard scans it follows).
        """
        if (
            kw.get("entity_type") is not None
            and kw.get("entity_id") is not None
        ):
            # rowkey-locality fast path, same as find(): one shard
            # holds the entity — no fan-out, no re-sort needed
            return self._shard(
                kw["entity_type"], kw["entity_id"]
            ).find_columnar(app_id, channel_id, **kw)
        all_frames = [
            s.find_columnar(app_id, channel_id, **kw)
            for s in self.shards
        ]
        frames = [f for f in all_frames if len(f)]
        if not frames:
            return all_frames[0]

        def cat(name):
            cols = [getattr(f, name) for f in frames]
            if any(c is None for c in cols):
                return None
            return np.concatenate(cols)

        merged = EventFrame(
            event=cat("event"),
            entity_type=cat("entity_type"),
            entity_id=cat("entity_id"),
            target_entity_type=cat("target_entity_type"),
            target_entity_id=cat("target_entity_id"),
            event_time_ms=cat("event_time_ms"),
            properties=cat("properties"),
            value=cat("value"),
        )
        order = np.argsort(merged.event_time_ms, kind="stable")
        if np.array_equal(order, np.arange(len(order))):
            return merged
        return merged.select(order)
