"""Crash-safe group-commit write-ahead log for the ingest edge (pio-levee).

The reference's HBase write path acknowledges a put only after the
region server's WAL has the record (hflush), then folds memstore
batches into files later.  Our sqlite stores commit per REST request —
durable, but the commit machinery (executemany + index maintenance +
version bump per 50-row batch) rides every request.  This module splits
the two jobs the way the reference does:

* **Ack = WAL fsync.**  A request's rows are framed, appended to the
  owning shard's log, and fsynced BEFORE the 2xx goes out.  Concurrent
  requests group-commit: the first submitter in becomes the *leader*,
  drains everything pending, and pays ONE write + fsync for the group
  (followers return as soon as the leader's flush covers them).
* **Sqlite commit = background drain.**  A committer thread folds
  acknowledged rows into the store in large ``insert_raw_rows`` batches
  (one transaction per drain), so steady-state ingest pays importer-
  style amortized commit costs instead of per-request ones.  Once the
  drain catches up, the logs are truncated (checkpoint).
* **Restart = replay.**  Rows acknowledged but not yet committed are
  re-inserted from the logs at startup.  Replay is at-least-once — a
  record may already be in sqlite if the crash hit between commit and
  truncate — and `INSERT OR REPLACE` on the event id makes that
  idempotent.  A torn trailing record (crash mid-append) is dropped:
  its submitter never got an ack, so dropping it loses nothing
  acknowledged.  This is the delta-chain/watermark torn-file discipline
  (PR 7) applied to the write path.

File format, one log per shard (``shard-<i>.wal``): each record is
``<crc32:4><len:4><payload>`` little-endian, payload = compact JSON
``[app_id, channel_id, row]`` with ``row`` the 11-column tuple of
`sqlite_events.event_to_row`.  Replay stops at the first short or
crc-mismatched frame and truncates the file there.

Failure discipline is fail-stop per shard: an append that errors
(including an injected ``wal.torn``) marks that shard's log broken and
every later write to the shard answers `ShardUnavailableError` until a
restart replays and truncates the log — a write path whose durability
log is suspect must stop acknowledging, not guess.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sqlite3
import struct
import threading
import time
import zlib
from pathlib import Path
from typing import Iterable, Optional

from ..obs import (
    WAL_BACKLOG_ROWS,
    WAL_COMMIT_ROWS,
    WAL_FSYNC_SECONDS,
    WAL_REPLAYED_TOTAL,
    scope,
)
from ..resilience import faults
from .levents import ShardUnavailableError

logger = logging.getLogger(__name__)

__all__ = ["EventWAL", "GroupCommitWAL", "replay_wal_dir"]

_HEADER = struct.Struct("<II")  # crc32(payload), len(payload)


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(zlib.crc32(payload), len(payload)) + payload


def _encode_record(app_id: int, channel_id: int, row) -> bytes:
    return json.dumps(
        [app_id, channel_id, list(row)], separators=(",", ":")
    ).encode("utf-8", "surrogatepass")


def _decode_record(payload: bytes) -> tuple[int, int, tuple]:
    app_id, channel_id, row = json.loads(payload.decode("utf-8",
                                                        "surrogatepass"))
    return int(app_id), int(channel_id), tuple(row)


def read_records(path) -> tuple[list[tuple[int, int, tuple]], int, bool]:
    """Parse a WAL file: ``(records, good_size, torn)``.

    ``good_size`` is the byte offset after the last intact frame;
    ``torn`` reports whether trailing bytes past it were dropped (short
    frame or crc mismatch — a crash mid-append).  Never raises on tail
    damage; a corrupt PREFIX cannot occur (frames are written in order
    and fsynced in order)."""
    try:
        data = Path(path).read_bytes()
    except FileNotFoundError:
        return [], 0, False
    records: list[tuple[int, int, tuple]] = []
    off = 0
    n = len(data)
    while off + _HEADER.size <= n:
        crc, ln = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + ln
        if end > n:
            break  # torn: header promises more bytes than exist
        payload = data[off + _HEADER.size:end]
        if zlib.crc32(payload) != crc:
            break  # torn mid-payload (or never completed)
        try:
            records.append(_decode_record(payload))
        except (ValueError, UnicodeDecodeError):
            break  # crc passed but content is garbage: treat as torn
        off = end
    return records, off, off != n


class EventWAL:
    """One shard's append-only log.  NOT internally locked: the group
    commit serializes every append under its flush lock (single-writer
    discipline), and replay runs before the writer exists."""

    def __init__(self, path, shard_ix: int):
        self.path = Path(path)
        self.shard_ix = shard_ix
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # recovery happens BEFORE opening for append (replay_wal_dir);
        # here we only position at the durable tail, truncating any
        # torn bytes so later appends never land after garbage
        _, good, torn = read_records(self.path)
        self._f = open(self.path, "ab")
        if torn:
            self._f.truncate(good)
        self.size = good
        self.broken: Optional[str] = None

    def append_group(self, payloads: Iterable[bytes],
                     fsync: bool = True) -> None:
        """Append framed records and (optionally) fsync — the leader's
        one durable write per group.  ``wal.torn`` (shard-scoped) tears
        the write mid-record: half the buffer lands, no fsync, and the
        log is marked broken — the simulated crash the replay suite
        recovers from."""
        if self.broken is not None:
            raise ShardUnavailableError(
                self.shard_ix, f"ingest WAL broken: {self.broken}"
            )
        buf = b"".join(_frame(p) for p in payloads)
        if not buf:
            return
        try:
            faults.check_shard("wal.torn", self.shard_ix)
        except BaseException as e:
            torn = buf[: max(len(buf) // 2, _HEADER.size - 1)]
            self._f.write(torn)
            self._f.flush()
            self.broken = f"{type(e).__name__}: {e}"
            raise ShardUnavailableError(
                self.shard_ix, f"ingest WAL torn: {e}"
            ) from e
        try:
            self._f.write(buf)
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())
        except OSError as e:
            self.broken = f"{type(e).__name__}: {e}"
            raise ShardUnavailableError(
                self.shard_ix, f"ingest WAL append failed: {e}"
            ) from e
        self.size += len(buf)

    def truncate(self) -> None:
        """Checkpoint: every logged record is committed — reset to
        empty.  Caller holds the flush lock (no concurrent appends)."""
        self._f.truncate(0)
        self._f.seek(0)
        os.fsync(self._f.fileno())
        self.size = 0

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def replay_wal_dir(wal_dir, store, shards: Optional[Iterable[int]] = None,
                   truncate: bool = True) -> dict:
    """Fold acknowledged-but-uncommitted rows back into ``store``.

    Scans ``shard-<i>.wal`` files under ``wal_dir`` (all of them, or
    just ``shards``), inserts every intact record via
    ``insert_raw_rows`` (grouped by (app, channel), one bulk scope —
    at-least-once + INSERT OR REPLACE = exactly-once effect), then
    truncates the replayed logs.  Returns
    ``{"replayed", "torn_shards", "shards"}`` for boot logs/smokes."""
    wal_dir = Path(wal_dir)
    replayed = 0
    torn_shards: list[int] = []
    seen_shards: list[int] = []
    if not wal_dir.is_dir():
        return {"replayed": 0, "torn_shards": [], "shards": []}
    paths = sorted(wal_dir.glob("shard-*.wal"))
    want = None if shards is None else {int(s) for s in shards}
    for p in paths:
        try:
            six = int(p.stem.split("-", 1)[1])
        except (IndexError, ValueError):
            continue
        if want is not None and six not in want:
            continue
        records, good, torn = read_records(p)
        seen_shards.append(six)
        if torn:
            torn_shards.append(six)
        if records:
            groups: dict[tuple[int, int], list[tuple]] = {}
            for app_id, channel_id, row in records:
                groups.setdefault((app_id, channel_id), []).append(row)
            for (app_id, channel_id), rows in sorted(groups.items()):
                store.init_channel(app_id, channel_id)
                store.insert_raw_rows(rows, app_id, channel_id)
            replayed += len(records)
            WAL_REPLAYED_TOTAL.labels(shard=str(six)).inc(len(records))
        if truncate and (records or torn):
            # replayed content is committed (insert_raw_rows commits);
            # only now is dropping the log safe
            with open(p, "r+b") as f:
                f.truncate(0)
                f.flush()
                os.fsync(f.fileno())
    if replayed or torn_shards:
        logger.info(
            "ingest WAL replay: %d records into %s (torn tails on "
            "shards %s)", replayed, wal_dir, torn_shards or "none",
        )
    return {"replayed": replayed, "torn_shards": torn_shards,
            "shards": seen_shards}


class GroupCommitWAL:
    """Owner-level group commit over per-shard logs.

    ``submit`` is the ingest edge's whole write path: route rows to
    shards, refuse non-owned or down shards, group-commit to the WAL
    (ack), and queue for the background sqlite drain.  ``barrier``
    gives the server's own read routes read-your-writes.

    Lock order: ``_flush_lock`` (leader election, serializes WAL
    appends and checkpoints) is taken OUTSIDE ``_lock`` (pending/seq
    bookkeeping, commit queue).  The committer thread takes them in the
    same order.
    """

    def __init__(self, store, wal_dir,
                 owned_shards: Optional[Iterable[int]] = None,
                 commit_interval_s: float = 0.02,
                 max_commit_rows: int = 20_000,
                 fsync: bool = True,
                 shard_ix=None,
                 replay: bool = True):
        self._store = store
        self.wal_dir = Path(wal_dir)
        self.n_shards = int(getattr(store, "n_shards", 1))
        self.owned = (
            frozenset(range(self.n_shards)) if owned_shards is None
            else frozenset(int(s) for s in owned_shards)
        )
        bad = [s for s in self.owned if not 0 <= s < self.n_shards]
        if bad:
            raise ValueError(
                f"owned shards {bad} out of range for "
                f"{self.n_shards}-shard store"
            )
        self.commit_interval_s = commit_interval_s
        self.max_commit_rows = max_commit_rows
        self.fsync = fsync
        # shard_ix(entity_type, entity_id, n) — injected so this module
        # needs no import of sharded_events (which stays WAL-free); the
        # single-file store routes everything to shard 0
        if shard_ix is None and self.n_shards > 1:
            from .sharded_events import _shard_ix as shard_ix
        self._shard_ix = shard_ix
        self.replay_report = (
            replay_wal_dir(self.wal_dir, store, shards=self.owned)
            if replay else {"replayed": 0, "torn_shards": [],
                            "shards": []}
        )
        self._wals = {
            six: EventWAL(self.wal_dir / f"shard-{six}.wal", six)
            for six in sorted(self.owned)
        }
        # pio-scope: the two ingest hot locks.  "wal_commit" is the
        # bookkeeping monitor every submit and the committer share;
        # "wal_flush" serializes group leaders — its wait histogram IS
        # the follower-waiting-on-a-leader's-fsync distribution.
        self._lock = scope.TimedLock("wal_commit")
        self._cv = scope.TimedCondition("wal_commit", lock=self._lock)
        self._flush_lock = scope.TimedLock("wal_flush")
        # (shard, payload bytes, (app, ch, row)) triples awaiting the
        # next leader's flush; commit queue holds flushed rows awaiting
        # the sqlite drain — both strictly FIFO so per-shard rowid
        # order matches ack order
        self._pending: list[tuple[int, bytes, tuple]] = []
        self._commit_q: collections.deque = collections.deque()
        self._submitted = 0
        self._flushed = 0
        self._committed = 0
        # (lo, hi] seq ranges whose flush failed — followers covered by
        # a failed leader must raise, not ack (bounded: old ranges are
        # harmless, seqs never reset)
        self._failures: collections.deque = collections.deque(maxlen=32)
        self._commit_now = False
        self._closing = False
        self._committer = threading.Thread(
            target=self._commit_loop, name="wal-committer", daemon=True,
        )
        self._committer.start()

    # -- write path -------------------------------------------------------
    def route(self, entity_type: str, entity_id: str) -> int:
        if self.n_shards <= 1:
            return 0
        return self._shard_ix(entity_type, entity_id, self.n_shards)

    def _guard(self, six: int) -> None:
        if six not in self.owned:
            raise ShardUnavailableError(
                six, "not owned by this worker (router misroute?)"
            )
        try:
            faults.check_shard("store.shard_down", six)
        except ShardUnavailableError:
            raise
        except BaseException as e:
            raise ShardUnavailableError(six, str(e)) from e
        wal = self._wals[six]
        if wal.broken is not None:
            raise ShardUnavailableError(
                six, f"ingest WAL broken: {wal.broken}"
            )

    def submit(self, app_id: int, channel_id: int, rows) -> None:
        """Durably log ``rows`` (11-column event_to_row tuples); when
        this returns, every row is fsynced in its shard's WAL and the
        caller may acknowledge.  Raises `ShardUnavailableError` for a
        down/foreign shard (nothing is logged) and propagates WAL
        append failures (nothing acknowledged)."""
        blobs: list[tuple[int, bytes, tuple]] = []
        for row in rows:
            six = self.route(row[2], row[3])
            self._guard(six)
            blobs.append((
                six,
                _encode_record(app_id, channel_id, row),
                (six, app_id, channel_id, row),
            ))
        if not blobs:
            return
        with self._lock:
            self._pending.extend(blobs)
            self._submitted += len(blobs)
            my_seq = self._submitted
        t0 = time.perf_counter()
        with self._flush_lock:
            with self._lock:
                covered = self._flushed >= my_seq
                if not covered:
                    batch, self._pending = self._pending, []
            if not covered and batch:
                self._flush_group(batch)
        WAL_FSYNC_SECONDS.child().observe(time.perf_counter() - t0)
        with self._lock:
            lo = my_seq - len(blobs)
            for flo, fhi, err in self._failures:
                if lo < fhi and my_seq > flo:
                    raise ShardUnavailableError(
                        blobs[0][0], f"group flush failed: {err}"
                    )

    def _flush_group(self, batch) -> None:
        """Leader: write + fsync one group (caller holds _flush_lock).
        On failure the whole group is marked failed — no row in it was
        durably acknowledged."""
        by_shard: dict[int, list[bytes]] = {}
        for six, payload, _ in batch:
            by_shard.setdefault(six, []).append(payload)
        try:
            for six in sorted(by_shard):
                self._wals[six].append_group(
                    by_shard[six], fsync=self.fsync
                )
        except BaseException as e:
            with self._lock:
                lo = self._flushed
                self._flushed += len(batch)
                # nothing in a failed group was acknowledged, so there
                # is nothing to drain: count the rows resolved or every
                # later barrier() would wait on them forever
                self._committed += len(batch)
                self._failures.append(
                    (lo, self._flushed, f"{type(e).__name__}: {e}")
                )
                self._cv.notify_all()
            raise
        with self._lock:
            self._flushed += len(batch)
            self._commit_q.extend(item for _, _, item in batch)
            WAL_BACKLOG_ROWS.child().set(float(len(self._commit_q)))
            self._cv.notify_all()

    # -- read-your-writes barrier ----------------------------------------
    def barrier(self, timeout_s: float = 10.0) -> None:
        """Block until everything acknowledged before this call is
        committed into sqlite (the server's GET routes call this so a
        201 is immediately visible to the poster).  A drain stuck past
        ``timeout_s`` raises ``sqlite3.OperationalError`` — the same
        transient-storage surface the 503 path already speaks."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            target = self._flushed
            self._commit_now = True
            self._cv.notify_all()
            while self._committed < target:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise sqlite3.OperationalError(
                        f"ingest WAL drain backlog "
                        f"({target - self._committed} rows) did not "
                        f"clear in {timeout_s}s"
                    )
                self._cv.wait(left)

    def pending_rows(self) -> int:
        with self._lock:
            return len(self._commit_q)

    # -- background sqlite drain -----------------------------------------
    def _commit_loop(self) -> None:
        scope.register_thread_role("wal_committer")
        while True:
            with self._lock:
                while (not self._commit_q and not self._closing):
                    self._cv.wait()
                if self._closing and not self._commit_q:
                    return
                if not self._commit_now and not self._closing:
                    # accumulation window: let a few more groups land so
                    # one transaction commits hundreds of rows, not 50
                    self._cv.wait(self.commit_interval_s)
                self._commit_now = False
                batch = []
                while self._commit_q and len(batch) < self.max_commit_rows:
                    batch.append(self._commit_q.popleft())
                WAL_BACKLOG_ROWS.child().set(float(len(self._commit_q)))
            if not batch:
                continue
            try:
                self._drain(batch)
            except Exception as e:
                # rows here are fsynced + acknowledged: NEVER drop.
                # Re-queue at the front (order preserved) and retry
                # with a bounded backoff; a restart would replay them
                # from the WAL anyway.
                logger.warning("WAL drain failed (%s); retrying", e)
                with self._lock:
                    self._commit_q.extendleft(reversed(batch))
                    WAL_BACKLOG_ROWS.child().set(
                        float(len(self._commit_q))
                    )
                time.sleep(min(self.commit_interval_s * 5, 0.5))
                continue
            with self._lock:
                self._committed += len(batch)
                fully_drained = (not self._commit_q
                                 and self._committed >= self._flushed)
                self._cv.notify_all()
            WAL_COMMIT_ROWS.child().observe(len(batch))
            if fully_drained:
                self._checkpoint()

    def _drain(self, batch) -> None:
        groups: dict[tuple[int, int], list[tuple]] = {}
        for _, app_id, channel_id, row in batch:
            groups.setdefault((app_id, channel_id), []).append(row)
        for (app_id, channel_id), rows in groups.items():
            self._store.insert_raw_rows(rows, app_id, channel_id)

    def _checkpoint(self) -> None:
        """Truncate fully-committed logs (bounds replay to the last
        in-flight window).  Leader lock excludes concurrent appends;
        re-check drained-ness under _lock once inside."""
        with self._flush_lock:
            with self._lock:
                if self._commit_q or self._committed < self._flushed:
                    return
            for wal in self._wals.values():
                if wal.size and wal.broken is None:
                    try:
                        wal.truncate()
                    except OSError as e:
                        wal.broken = f"{type(e).__name__}: {e}"

    def close(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the committer (draining acknowledged rows first unless
        ``drain=False`` — the crash-simulation hook the kill -9 tests
        use) and close the logs."""
        if drain:
            try:
                self.barrier(timeout_s=timeout_s)
            except sqlite3.OperationalError:
                logger.warning(
                    "ingest WAL close: drain did not finish; remaining "
                    "rows will replay on next start"
                )
        with self._lock:
            self._closing = True
            if not drain:
                self._commit_q.clear()
            self._cv.notify_all()
        self._committer.join(timeout=timeout_s)
        for wal in self._wals.values():
            wal.close()
