"""pio-hive: the device-memory-budgeted multi-tenant model registry.

One :class:`TenantRegistry` turns one serving process into a platform:
N (app, engine_variant) models multiplexed behind one port, loaded
lazily on first query, kept under a configurable memory budget with
LRU eviction + pinning, each with its OWN circuit breaker, token-bucket
quota, warmup ladder, fold-in state, and metric label set — so one
tenant's open breaker, quota exhaustion, or fold-in push cannot move
another tenant's p99 or error rate (the isolation contract
``tools/hive_smoke.py`` proves live).

Design notes:

* **Budget math**: resident cost per tenant is counted by
  :func:`model_resident_bytes` — every numpy/jax array reachable from
  the model objects (factor tables, string indexes, cached device
  tables/ANN slabs), deduplicated by object identity.  The pio-xray
  ``pio_device_memory_bytes`` gauges are resampled after every load/
  evict so the allocator's view and the registry's accounting can be
  compared on one ``/metrics`` scrape.
* **Eviction safety**: eviction only considers tenants that are
  neither pinned nor serving an in-flight query (a per-tenant lease
  count).  A query that already snapshotted its components keeps them
  alive by reference even if its tenant is evicted mid-flight — an
  eviction can therefore never fail an in-flight request, only cost
  the NEXT request a reload.
* **LRU determinism**: recency is a monotonically increasing integer
  tick, not a wall clock, so a seeded access pattern produces the
  exact same eviction sequence on every run (property-tested).
* **Loading off-lock**: a lazy load (seconds of XLA warmup) runs
  OUTSIDE the registry lock behind a per-key in-progress event;
  concurrent queries for other tenants never stall behind it, and
  concurrent queries for the SAME tenant wait for the one load instead
  of duplicating it.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional

from ..obs import (
    FOLDIN_APPLIES_TOTAL,
    TENANT_LOADS_TOTAL,
    TENANT_MEMORY_BUDGET,
    TENANT_PLACEMENT_BALANCE,
    TENANT_QUERIES_TOTAL,
    TENANT_QUERY_LATENCY,
    TENANT_QUOTA_REJECTED,
    TENANT_RESIDENT_BYTES,
    TENANTS_RESIDENT,
    get_tracer,
    scope,
)
from ..resilience.policy import CircuitBreaker
from .errors import QuotaExceeded, TenantUnavailable, UnknownTenant
from .experiment import Experiment
from .online_eval import OnlineEval
from .quota import TokenBucket

__all__ = [
    "TenantLease",
    "TenantRegistry",
    "TenantRuntime",
    "TenantSpec",
    "load_tenant_manifest",
    "model_resident_bytes",
]

logger = logging.getLogger(__name__)

# per-tenant serving outcome label values (the ones complete() books)
_STATUSES = (
    "ok", "error", "timeout", "rejected", "quota", "bad_request", "shed",
)
# outcomes that count as tenant-breaker failures: real faults and
# overload sheds open it (isolation), client mistakes close it
_BREAKER_FAILURES = frozenset(("error", "timeout", "rejected"))


def model_resident_bytes(models) -> int:
    """Accounted bytes of a tenant's model objects: every array
    (numpy or jax, host or device) reachable from the models' attribute
    graphs to a small depth, deduplicated by identity — factor tables,
    id indexes, cached device tables, quantized ANN slabs."""
    seen: set[int] = set()

    def walk(obj: Any, depth: int) -> int:
        if obj is None or isinstance(obj, (str, bytes, int, float, bool)):
            return 0
        if id(obj) in seen:
            return 0
        seen.add(id(obj))
        nbytes = getattr(obj, "nbytes", None)
        if nbytes is not None:
            try:
                return int(nbytes)
            except (TypeError, ValueError):
                return 0
        if depth <= 0:
            return 0
        total = 0
        if isinstance(obj, dict):
            for v in obj.values():
                total += walk(v, depth - 1)
            return total
        if isinstance(obj, (list, tuple, set)):
            for v in obj:
                total += walk(v, depth - 1)
            return total
        d = getattr(obj, "__dict__", None)
        if d:
            for v in d.values():
                total += walk(v, depth - 1)
        return total

    return sum(walk(m, 4) for m in models)


class TenantSpec:
    """Declaration of one (app, engine_variant) tenant.

    Either ``engine_json`` (resolved by the server's loader at first
    query) or prebuilt ``engine``/``engine_params``/``instance_id``
    (programmatic callers: benches, tests) must be provided.
    """

    def __init__(self, app: str, variant: str = "default",
                 engine_json: Optional[str] = None,
                 engine=None, engine_params=None,
                 instance_id: Optional[str] = None,
                 ctx=None,
                 app_id: Optional[int] = None,
                 access_key: Optional[str] = None,
                 weight: float = 1.0,
                 pinned: bool = False,
                 quota_qps: Optional[float] = None,
                 quota_burst: Optional[float] = None,
                 engine_name: Optional[str] = None):
        if not app:
            raise ValueError("tenant spec needs a non-empty app name")
        if not variant:
            raise ValueError("tenant spec needs a non-empty variant name")
        if engine_json is None and engine is None and engine_name is None:
            raise ValueError(
                f"tenant {app}/{variant}: provide engine_json, a "
                "registered engine name, or a prebuilt engine"
            )
        if not (weight >= 0.0):
            raise ValueError(
                f"tenant {app}/{variant}: weight must be >= 0, "
                f"got {weight}"
            )
        self.app = str(app)
        self.variant = str(variant)
        self.engine_json = engine_json
        # pio-forge: a tenants.json entry may name any REGISTERED
        # engine ("engine": "trending") instead of an engine.json path;
        # the loader resolves it through the registry, and the trained
        # instance is looked up under the `engine:<name>` variant key
        self.engine_name = engine_name
        self.engine = engine
        self.engine_params = engine_params
        self.instance_id = instance_id
        self.ctx = ctx
        self.app_id = app_id
        self.access_key = access_key
        self.weight = float(weight)
        self.pinned = bool(pinned)
        self.quota_qps = quota_qps
        self.quota_burst = quota_burst

    @property
    def key(self) -> tuple[str, str]:
        return (self.app, self.variant)

    @property
    def key_str(self) -> str:
        return f"{self.app}/{self.variant}"


class TenantRuntime:
    """One resident tenant's serving state: the same component set an
    ``EngineServer`` holds for its single model, plus the per-tenant
    resilience/quota/metric objects.  A passive holder — all mutable
    bookkeeping (inflight, recency, fold-in fields) is guarded by the
    OWNING registry's lock."""

    def __init__(self, spec: TenantSpec, engine, engine_params,
                 instance_id: str, algorithms, models, serving, batcher,
                 query_decoder, ctx,
                 breaker: Optional[CircuitBreaker] = None,
                 quota: Optional[TokenBucket] = None):
        self.spec = spec
        self.key = spec.key
        self.key_str = spec.key_str
        self.engine = engine
        self.engine_params = engine_params
        self.instance_id = instance_id
        self.algorithms = algorithms
        self.models = models
        self.serving = serving
        self.batcher = batcher
        self.query_decoder = query_decoder
        self.ctx = ctx
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=5, reset_timeout_s=10.0
        )
        self.quota = quota
        self.pinned = spec.pinned
        self.is_anchor = False
        self.resident_bytes = model_resident_bytes(models)
        self.loaded_at = time.time()  # wall clock: a TIMESTAMP
        # registry-guarded bookkeeping
        self.last_used = 0
        self.inflight = 0
        self.requests = 0
        # pio-live per-tenant fold-in state (mirrors EngineServer's)
        self.foldin_applied_seq: dict = {}
        self.foldin_deltas_applied = 0
        self.last_foldin_error: Optional[str] = None
        self.model_advanced_mono = time.monotonic()
        # labeled children resolved once (.labels() is too hot for the
        # per-request path)
        app, variant = spec.key
        self.m_queries = {
            s: TENANT_QUERIES_TOTAL.labels(app=app, variant=variant,
                                           status=s)
            for s in _STATUSES
        }
        self.m_latency = TENANT_QUERY_LATENCY.labels(
            app=app, variant=variant
        )
        self.m_quota = TENANT_QUOTA_REJECTED.labels(
            app=app, variant=variant
        )
        self.m_resident = TENANT_RESIDENT_BYTES.labels(
            app=app, variant=variant
        )

    def snapshot(self) -> dict:
        """Status view; reads of registry-guarded counters are benign
        torn reads of ints (display only)."""
        out = {
            "app": self.spec.app,
            "variant": self.spec.variant,
            "instanceId": self.instance_id,
            "residentBytes": self.resident_bytes,
            "pinned": self.pinned,
            "anchor": self.is_anchor,
            "inflight": self.inflight,
            "requests": self.requests,
            "breaker": self.breaker.state,
            "foldinDeltasApplied": self.foldin_deltas_applied,
            "modelFreshnessSec": round(
                max(time.monotonic() - self.model_advanced_mono, 0.0), 3
            ),
        }
        if self.quota is not None:
            out["quota"] = self.quota.snapshot()
        if self.last_foldin_error:
            out["lastFoldinError"] = self.last_foldin_error
        return out


class TenantLease:
    """One query's hold on a tenant: pins it against eviction (via the
    inflight count) and books the outcome exactly once."""

    __slots__ = ("registry", "runtime", "variant", "assigned", "_done")

    def __init__(self, registry: "TenantRegistry", runtime: TenantRuntime,
                 variant: str, assigned: bool):
        self.registry = registry
        self.runtime = runtime
        self.variant = variant
        self.assigned = assigned  # True = experiment-assigned, not explicit
        self._done = False

    @property
    def key_str(self) -> str:
        return self.runtime.key_str

    def observe_latency(self, seconds: float, exemplar=None) -> None:
        self.runtime.m_latency.observe(seconds, exemplar=exemplar)

    def complete(self, status: str) -> None:
        """Book the per-tenant outcome + breaker signal and release the
        eviction pin.  Idempotent — success and error paths may race on
        the event-loop edge."""
        if self._done:
            return
        self._done = True
        rt = self.runtime
        rt.m_queries.get(status, rt.m_queries["error"]).inc()
        if status == "quota":
            rt.m_quota.inc()
        if status in _BREAKER_FAILURES:
            rt.breaker.record_failure()
        else:
            rt.breaker.record_success()
        self.registry._release(rt)


class TenantRegistry:
    """See module docstring.  ``loader`` is injected (the serving layer
    provides one that builds real components; tests inject fakes) —
    ``loader(spec) -> TenantRuntime``."""

    # how long a query waits on another thread's in-progress load of
    # the same tenant before shedding (the load itself is bounded by
    # whatever the loader does; this bounds the WAITERS)
    load_wait_s = 120.0

    def __init__(self, specs, memory_budget_bytes: Optional[float] = None,
                 salt: str = "pio-hive",
                 loader: Optional[Callable[[TenantSpec], TenantRuntime]] = None,
                 default_quota_qps: Optional[float] = None,
                 eval_interval_s: float = 5.0,
                 autopilot: Optional[dict] = None):
        specs = list(specs)
        if not specs:
            raise ValueError("tenant registry needs >= 1 tenant spec")
        # pio-scope: every tenant lookup/load/evict serializes here —
        # multi-tenant p99 stalls show up as this lock's wait histogram
        self._lock = scope.TimedLock("tenant_registry", reentrant=True)
        self._specs: dict[tuple[str, str], TenantSpec] = {}
        for s in specs:
            if s.key in self._specs:
                raise ValueError(f"duplicate tenant spec {s.key_str}")
            if s.quota_qps is None and default_quota_qps is not None:
                s.quota_qps = default_quota_qps
            self._specs[s.key] = s
        self.anchor_key = specs[0].key
        self.salt = salt
        self.loader = loader
        self.default_quota_qps = default_quota_qps
        self.eval_interval_s = eval_interval_s
        self.memory_budget_bytes = (
            int(memory_budget_bytes) if memory_budget_bytes else 0
        )
        TENANT_MEMORY_BUDGET.child().set(float(self.memory_budget_bytes))
        # one experiment per app over that app's variants
        by_app: dict[str, dict[str, float]] = {}
        for s in specs:
            by_app.setdefault(s.app, {})[s.variant] = s.weight
        self._experiments = {
            app: Experiment(app, weights, salt=salt)
            for app, weights in by_app.items()
        }
        self._by_access_key = {
            s.access_key: s.app for s in specs if s.access_key
        }
        self._runtimes: dict[tuple[str, str], TenantRuntime] = {}
        self._loading: dict[tuple[str, str], threading.Event] = {}
        self._tick = 0
        self.loads = 0
        self.evictions = 0
        self.overcommits = 0
        # pio-confluence: budget evictions performed to make room for
        # an INCOMING tenant (the registry rebalancing placement, as
        # opposed to an admin shrink/evict) — paired with the
        # pio_tenant_placement_balance gauge
        self.rebalances = 0
        self.online = OnlineEval(salt=salt)
        # pio-pilot: the self-driving experiment controller (opt-in via
        # enable_autopilot() or the tenants.json "autopilot" block; the
        # serving online-eval loop drives its tick right after each
        # conversion refresh)
        self.autopilot = None
        if autopilot is not None:
            self.enable_autopilot(config=autopilot)

    # -- spec / experiment views ------------------------------------------
    def specs(self) -> list[TenantSpec]:
        with self._lock:
            return list(self._specs.values())

    def spec(self, key: tuple[str, str]) -> TenantSpec:
        with self._lock:
            s = self._specs.get(key)
        if s is None:
            raise UnknownTenant(f"unknown tenant {key}")
        return s

    def apps(self) -> list[str]:
        with self._lock:
            return sorted(self._experiments)

    def experiment(self, app: str) -> Experiment:
        with self._lock:
            exp = self._experiments.get(app)
        if exp is None:
            raise UnknownTenant(f"unknown app {app!r}")
        return exp

    def set_weights(self, app: str, weights: dict) -> dict:
        """Hot-update an app's variant weights; returns the new
        snapshot (the admin-API/router-broadcast primitive)."""
        exp = self.experiment(app)
        exp.set_weights({str(k): float(v) for k, v in weights.items()})
        return exp.snapshot()

    def deficit_weight(self, key: tuple[str, str]) -> float:
        """One tenant's share weight for the shared batcher's claim-time
        deficit round-robin (pio-confluence): its variant weight
        normalized by its app's total, so an app splitting traffic
        90/10 across variants claims device share 90/10 too, and apps
        are peers.  Reads the LIVE experiment weights — a hot ``POST
        /tenants/weights`` reshapes the next dispatcher claim with no
        push plumbing.  Unknown tenants weigh 1.0 (never let a
        scheduling lookup shed a query)."""
        app, variant = key
        with self._lock:
            exp = self._experiments.get(app)
            if exp is None:
                return 1.0
            weights = exp.weights()
        w = weights.get(variant)
        if w is None:
            return 1.0
        total = sum(weights.values())
        return w / total if total > 0 else 1.0

    # -- lifecycle admin (POST /admin/tenants) -----------------------------
    def add_tenant(self, spec: TenantSpec) -> dict:
        """Live-add a tenant without redeploy (ROADMAP 5d).  The spec
        registers immediately; the model loads lazily on first query
        exactly like a boot-manifest tenant (budget eviction applies).
        Adding a new variant to an existing app rebuilds that app's
        experiment with the extended weight set — sticky assignment is
        pure hash math, so existing variants' users keep their
        assignment except for the interval mass the new weight
        claims."""
        with self._lock:
            if spec.key in self._specs:
                raise ValueError(
                    f"tenant {spec.key_str} already exists"
                )
            if spec.quota_qps is None and self.default_quota_qps is not None:
                spec.quota_qps = self.default_quota_qps
            self._specs[spec.key] = spec
            exp = self._experiments.get(spec.app)
            weights = dict(exp.weights()) if exp is not None else {}
            weights[spec.variant] = spec.weight
            self._experiments[spec.app] = Experiment(
                spec.app, weights, salt=self.salt
            )
            if spec.access_key:
                self._by_access_key[spec.access_key] = spec.app
            new_weights = self._experiments[spec.app].weights()
        TENANT_LOADS_TOTAL.labels(
            app=spec.app, variant=spec.variant, kind="admin_add"
        ).inc()
        logger.info("tenant %s added live", spec.key_str)
        return {"added": spec.key_str, "weights": new_weights}

    def remove_tenant(self, key: tuple[str, str],
                      drain_timeout_s: float = 10.0) -> dict:
        """Live-remove a tenant: new queries stop resolving to it
        IMMEDIATELY (spec + experiment variant dropped under the lock),
        then the resident model waits for its in-flight leases to
        drain — the same in-flight safety the eviction path enforces,
        made blocking — before unload.  The anchor tenant is refused
        (it IS the process's base components).  Returns
        ``{"removed", "drained", "wasResident"}``; ``drained=False``
        means the drain timed out and the runtime was unloaded with
        leases still open (logged loudly)."""
        key = (str(key[0]), str(key[1]))
        with self._lock:
            spec = self._specs.get(key)
            if spec is None:
                raise UnknownTenant(f"unknown tenant {key}")
            if key == self.anchor_key:
                raise ValueError(
                    "cannot remove the anchor tenant (it is the "
                    "server's own model); redeploy instead"
                )
            del self._specs[key]
            app, variant = key
            exp = self._experiments.get(app)
            if exp is not None:
                weights = dict(exp.weights())
                weights.pop(variant, None)
                if weights and sum(weights.values()) > 0:
                    self._experiments[app] = Experiment(
                        app, weights, salt=self.salt
                    )
                else:
                    # last variant of the app: the app itself goes
                    del self._experiments[app]
            if spec.access_key:
                self._by_access_key.pop(spec.access_key, None)
            rt = self._runtimes.get(key)
        drained = True
        if rt is not None:
            deadline = time.monotonic() + max(drain_timeout_s, 0.0)
            while True:
                with self._lock:
                    if rt.inflight == 0:
                        self._runtimes.pop(key, None)
                        self._book_residency_locked(rt, "admin_remove")
                        break
                if time.monotonic() > deadline:
                    drained = False
                    logger.warning(
                        "tenant %s removal drain timed out with %d "
                        "leases in flight; unloading anyway",
                        spec.key_str, rt.inflight,
                    )
                    with self._lock:
                        self._runtimes.pop(key, None)
                        self._book_residency_locked(rt, "admin_remove")
                    break
                time.sleep(0.005)
            self._close_runtime(rt)
            self._sample_device_memory()
        logger.info("tenant %s removed (drained=%s)", spec.key_str,
                    drained)
        return {"removed": spec.key_str, "drained": drained,
                "wasResident": rt is not None}

    # -- resolution (the per-query hot path) ------------------------------
    def resolve(self, query_json: dict) -> TenantLease:
        """Route one query to its tenant: explicit ``app``/``appId`` +
        ``variant`` fields win, an ``accessKey`` field maps to its app,
        anything else lands on the anchor tenant; a missing variant is
        assigned by the app's experiment from the ``user`` field
        (sticky weighted A/B).  Applies quota THEN breaker admission,
        loads the model lazily, and returns a lease pinning the tenant
        for the query's duration."""
        with self._lock:
            # one snapshot of the routing tables: tenant add/remove
            # mutates them live, and a query's app->experiment->spec
            # walk must be self-consistent
            by_access_key = dict(self._by_access_key)
            experiments = dict(self._experiments)
            spec_keys = set(self._specs)
        app = query_json.get("app") or query_json.get("appId")
        if app is None:
            ak = query_json.get("accessKey")
            if ak is not None:
                app = by_access_key.get(str(ak))
                if app is None:
                    raise UnknownTenant(f"unknown access key {str(ak)[:8]}…")
        if app is None:
            app, default_variant = self.anchor_key
        else:
            app, default_variant = str(app), None
        exp = experiments.get(app)
        if exp is None:
            raise UnknownTenant(f"unknown app {app!r}")
        variant = query_json.get("variant")
        assigned = False
        if variant is None:
            if default_variant is not None and len(exp.variants()) == 1:
                variant = default_variant
            else:
                variant = exp.assign(str(query_json.get("user", "")))
                assigned = True
        key = (app, str(variant))
        if key not in spec_keys:
            raise UnknownTenant(
                f"unknown variant {variant!r} for app {app!r}"
            )
        rt = self.get_runtime(key)
        # quota before the breaker: allow() may claim the single
        # half-open probe slot, which a quota shed would then strand
        if rt.quota is not None and not rt.quota.try_acquire():
            rt.m_queries["quota"].inc()
            rt.m_quota.inc()
            raise QuotaExceeded(
                f"tenant {rt.key_str} is over its "
                f"{rt.quota.rate_qps:g} QPS quota"
            )
        if not rt.breaker.allow():
            rt.m_queries["shed"].inc()
            raise TenantUnavailable(
                f"tenant {rt.key_str} breaker is open "
                "(shedding after repeated failures)"
            )
        with self._lock:
            self._tick += 1
            rt.last_used = self._tick
            rt.inflight += 1
            rt.requests += 1
        return TenantLease(self, rt, str(variant), assigned)

    def _release(self, rt: TenantRuntime) -> None:
        with self._lock:
            rt.inflight = max(rt.inflight - 1, 0)

    # -- residency / budget ------------------------------------------------
    def get_runtime(self, key: tuple[str, str]) -> TenantRuntime:
        """The resident runtime for ``key``, loading it lazily (and
        evicting LRU tenants past the budget) on first use."""
        with self._lock:
            rt = self._runtimes.get(key)
            if rt is not None:
                self._tick += 1
                rt.last_used = self._tick
                return rt
            spec = self._specs.get(key)
            if spec is None:
                raise UnknownTenant(f"unknown tenant {key}")
            ev = self._loading.get(key)
            mine = ev is None
            if mine:
                ev = threading.Event()
                self._loading[key] = ev
        if not mine:
            # another query is already loading this tenant: wait for
            # that ONE load instead of duplicating seconds of warmup
            ev.wait(self.load_wait_s)
            with self._lock:
                rt = self._runtimes.get(key)
            if rt is None:
                raise TenantUnavailable(
                    f"tenant {spec.key_str} failed to load"
                )
            return rt
        evicted: list[TenantRuntime] = []
        try:
            if self.loader is None:
                raise TenantUnavailable(
                    f"tenant {spec.key_str} is not resident and no "
                    "loader is configured"
                )
            t0 = time.perf_counter()
            with get_tracer().span("hive.load", {"tenant": spec.key_str}):
                rt = self.loader(spec)
            with self._lock:
                evicted = self._evict_to_fit_locked(
                    rt.resident_bytes, exclude=key
                )
                self._runtimes[key] = rt
                self._tick += 1
                rt.last_used = self._tick
                self.loads += 1
                self._book_residency_locked(rt, "load")
            logger.info(
                "loaded tenant %s (%.1f MB resident) in %.2fs",
                spec.key_str, rt.resident_bytes / 1e6,
                time.perf_counter() - t0,
            )
        except TenantUnavailable:
            raise
        except Exception as e:
            logger.exception("tenant %s load failed", spec.key_str)
            raise TenantUnavailable(
                f"tenant {spec.key_str} load failed: "
                f"{type(e).__name__}: {e}"
            ) from e
        finally:
            with self._lock:
                self._loading.pop(key, None)
            ev.set()
            # close evicted batchers OFF the registry lock (the
            # dispatcher join must not stall other tenants' resolves)
            for old in evicted:
                self._close_runtime(old)
        self._sample_device_memory()
        return rt

    def _book_residency_locked(self, rt: TenantRuntime, kind: str) -> None:
        app, variant = rt.key
        TENANT_LOADS_TOTAL.labels(app=app, variant=variant,
                                  kind=kind).inc()
        rt.m_resident.set(
            float(rt.resident_bytes) if kind == "load" else 0.0
        )
        TENANTS_RESIDENT.child().set(float(len(self._runtimes)))
        TENANT_PLACEMENT_BALANCE.child().set(
            self._placement_balance_locked()
        )

    def _placement_balance_locked(self) -> float:
        """Jain fairness index over resident tenants' accounted bytes:
        (Σb)² / (n·Σb²).  1.0 = every resident tenant holds an equal
        byte share, 1/n = one tenant holds everything, 0.0 = nothing
        resident.  Zero-byte runtimes (e.g. stub models in tests)
        count as perfectly even among themselves."""
        sizes = [float(r.resident_bytes)
                 for r in self._runtimes.values()]
        n = len(sizes)
        if n == 0:
            return 0.0
        total = sum(sizes)
        if total <= 0.0:
            return 1.0
        sq = sum(b * b for b in sizes)
        return (total * total) / (n * sq) if sq > 0.0 else 1.0

    def placement_balance(self) -> float:
        with self._lock:
            return self._placement_balance_locked()

    def _evict_to_fit_locked(self, incoming_bytes: int,
                             exclude) -> list[TenantRuntime]:
        """Under the lock: pop LRU tenants until ``incoming_bytes``
        fits the budget.  Pinned, in-flight, and anchor tenants are
        never candidates; if nothing evictable remains the load
        proceeds OVER budget (loudly) — shedding the query would turn
        a memory policy into an outage."""
        if not self.memory_budget_bytes:
            return []
        evicted: list[TenantRuntime] = []
        while (self._resident_bytes_locked() + incoming_bytes
               > self.memory_budget_bytes):
            candidates = [
                r for k, r in self._runtimes.items()
                if k != exclude and not r.pinned and not r.is_anchor
                and r.inflight == 0
            ]
            if not candidates:
                self.overcommits += 1
                app, variant = exclude
                TENANT_LOADS_TOTAL.labels(
                    app=app, variant=variant, kind="overcommit"
                ).inc()
                logger.warning(
                    "memory budget %.1f MB exceeded with no evictable "
                    "tenant (all pinned or in-flight); loading %s over "
                    "budget", self.memory_budget_bytes / 1e6, exclude,
                )
                break
            victim = min(candidates, key=lambda r: r.last_used)
            self._runtimes.pop(victim.key, None)
            self.evictions += 1
            self._book_residency_locked(victim, "evict")
            evicted.append(victim)
            logger.info("evicted tenant %s (%.1f MB) under budget",
                        victim.key_str, victim.resident_bytes / 1e6)
        if evicted and exclude is not None:
            # evictions that made room for an incoming tenant ARE the
            # registry rebalancing its placement (vs an admin shrink)
            self.rebalances += 1
        return evicted

    def _resident_bytes_locked(self) -> int:
        return sum(r.resident_bytes for r in self._runtimes.values())

    def resident_bytes_total(self) -> int:
        with self._lock:
            return self._resident_bytes_locked()

    def resident_keys(self) -> list[tuple[str, str]]:
        with self._lock:
            return list(self._runtimes)

    def evict(self, key: tuple[str, str]) -> bool:
        """Explicit eviction (admin/test surface).  Refuses pinned/
        in-flight tenants — same safety rule as budget eviction."""
        with self._lock:
            rt = self._runtimes.get(key)
            if rt is None or rt.pinned or rt.is_anchor or rt.inflight:
                return False
            self._runtimes.pop(key)
            self.evictions += 1
            self._book_residency_locked(rt, "evict")
        self._close_runtime(rt)
        self._sample_device_memory()
        return True

    def set_memory_budget(self, budget_bytes: Optional[float]) -> list:
        """Hot-update the budget; an aggressive shrink evicts down to
        it immediately (in-flight/pinned tenants still exempt).
        Returns the evicted keys."""
        with self._lock:
            self.memory_budget_bytes = int(budget_bytes or 0)
            TENANT_MEMORY_BUDGET.child().set(
                float(self.memory_budget_bytes)
            )
            evicted = self._evict_to_fit_locked(0, exclude=None)
        for rt in evicted:
            self._close_runtime(rt)
        if evicted:
            self._sample_device_memory()
        return [rt.key for rt in evicted]

    def adopt_anchor(self, runtime: TenantRuntime) -> None:
        """Install the serving process's base components as the anchor
        tenant's runtime — one copy of the model serves both the
        default (tenant-less) path and explicit queries for the anchor
        (app, variant).  Always pinned: the anchor is the process's
        raison d'être, not an eviction candidate."""
        runtime.pinned = True
        runtime.is_anchor = True
        with self._lock:
            self._runtimes[self.anchor_key] = runtime
            self._tick += 1
            runtime.last_used = self._tick
            self._book_residency_locked(runtime, "load")

    def _close_runtime(self, rt: TenantRuntime) -> None:
        if rt.batcher is not None:
            try:
                rt.batcher.close()
            except Exception:
                logger.exception("closing evicted tenant %s batcher",
                                 rt.key_str)

    def _sample_device_memory(self) -> None:
        """Refresh the pio-xray per-device gauges so the allocator's
        view tracks registry load/evict events, not just the sampler
        cadence.  Best-effort: accounting must never fail a query."""
        try:
            from ..obs import xray

            xray.sample_devices_once()
        except Exception:
            pass

    # -- pio-live: per-tenant fold-in -------------------------------------
    def apply_available_deltas(self) -> int:
        """Walk every resident (non-anchor) tenant's delta chain and
        apply pending links in place — the per-tenant half of the
        serving fold-in poll (the anchor rides ``EngineServer``'s own
        chain walk).  One tenant's chain error is recorded on THAT
        tenant and the walk continues: a fold-in push must not pause
        the rest of the hive."""
        from ..live.apply import apply_model_delta, model_supports_deltas
        from ..workflow.model_io import load_model_delta_chain, model_key

        with self._lock:
            runtimes = [r for r in self._runtimes.values()
                        if not r.is_anchor]
        n_applied = 0
        for rt in runtimes:
            try:
                base_dir = (
                    rt.ctx.storage.model_data_dir() / rt.instance_id
                )
                names = [n for n, _ in rt.engine_params.algorithms]
                for ax, (name, model) in enumerate(
                    zip(names, rt.models)
                ):
                    if not model_supports_deltas(model):
                        continue
                    key = model_key(rt.instance_id, ax, name)
                    with self._lock:
                        after = rt.foldin_applied_seq.get(key, 0)
                    chain, err = load_model_delta_chain(
                        base_dir, key, after_seq=after
                    )
                    if err:
                        with self._lock:
                            rt.last_foldin_error = err
                    for d in chain:
                        t0 = time.perf_counter()
                        with self._lock:
                            apply_model_delta(model, d)
                            rt.foldin_applied_seq[key] = d.seq
                            rt.foldin_deltas_applied += 1
                            rt.model_advanced_mono = time.monotonic()
                            rt.last_foldin_error = None
                        FOLDIN_APPLIES_TOTAL.labels(result="ok").inc()
                        get_tracer().record(
                            "live.apply", time.perf_counter() - t0,
                            attrs={"tenant": rt.key_str, "seq": d.seq},
                        )
                        n_applied += 1
            except Exception as e:
                FOLDIN_APPLIES_TOTAL.labels(result="error").inc()
                with self._lock:
                    rt.last_foldin_error = f"{type(e).__name__}: {e}"
                logger.exception(
                    "fold-in apply failed for tenant %s; it keeps "
                    "serving its stale model", rt.key_str,
                )
        return n_applied

    # -- online eval -------------------------------------------------------
    def refresh_online_eval(self, event_store) -> dict:
        """Fold fresh conversion events into the per-variant outcome
        table (see :mod:`.online_eval`); returns the snapshot."""
        app_ids = {}
        with self._lock:
            for s in self._specs.values():
                if s.app_id is not None:
                    app_ids[s.app] = s.app_id
        return self.online.refresh(event_store, app_ids)

    # -- autopilot (pio-pilot) ---------------------------------------------
    def enable_autopilot(self, config=None, apply_weights=None,
                         manifest_id=None):
        """Attach a self-driving experiment controller (see
        :mod:`.autopilot`).  ``config`` is an :class:`AutopilotConfig`
        or a camelCase knob dict (the tenants.json ``"autopilot"``
        block); ``apply_weights`` overrides how ramp steps land
        (default: in-process ``set_weights`` — the serving edge or a
        smoke passes the real HTTP broadcast)."""
        from .autopilot import AutoPilot, AutopilotConfig, set_autopilot

        if config is not None and not isinstance(config, AutopilotConfig):
            config = AutopilotConfig.from_doc(dict(config))
        self.autopilot = AutoPilot(
            self, config=config, apply_weights=apply_weights,
            manifest_id=manifest_id,
        )
        set_autopilot(self.autopilot)
        return self.autopilot

    def autopilot_tick(self) -> Optional[dict]:
        """One controller pass, or ``None`` when no autopilot is
        attached (the serving loop calls this unconditionally)."""
        pilot = self.autopilot
        if pilot is None:
            return None
        return pilot.tick()

    # -- views -------------------------------------------------------------
    def summary(self) -> dict:
        """The small status-JSON block."""
        with self._lock:
            return {
                "tenants": len(self._specs),
                "resident": len(self._runtimes),
                "residentBytes": self._resident_bytes_locked(),
                "memoryBudgetBytes": self.memory_budget_bytes,
                "loads": self.loads,
                "evictions": self.evictions,
                "overcommits": self.overcommits,
                "rebalances": self.rebalances,
                "placementBalance": self._placement_balance_locked(),
            }

    def debug_payload(self) -> dict:
        """The full ``GET /debug/tenants`` document."""
        with self._lock:
            resident = {
                rt.key_str: rt.snapshot()
                for rt in self._runtimes.values()
            }
            specs = [
                {
                    "app": s.app, "variant": s.variant,
                    "weight": s.weight, "pinned": s.pinned,
                    "quotaQps": s.quota_qps,
                    "resident": s.key in self._runtimes,
                }
                for s in self._specs.values()
            ]
            experiments = dict(self._experiments)
        out = {
            **self.summary(),
            "anchor": "/".join(self.anchor_key),
            "specs": specs,
            "resident_tenants": resident,
            "experiments": {
                app: exp.snapshot()
                for app, exp in experiments.items()
            },
            "onlineEval": self.online.snapshot(),
            "autopilot": (
                self.autopilot.manifest_id
                if self.autopilot is not None else None
            ),
        }
        try:
            from ..obs import xray

            out["deviceMemory"] = xray.sample_devices_once()
        except Exception:
            pass
        return out

    def close(self) -> None:
        with self._lock:
            runtimes = list(self._runtimes.values())
            self._runtimes.clear()
        for rt in runtimes:
            if not rt.is_anchor:  # the server owns the anchor batcher
                self._close_runtime(rt)
        self.online.close()
        pilot = self.autopilot
        if pilot is not None:
            from .autopilot import set_autopilot

            set_autopilot(None)
            pilot.close()


# -- tenants.json manifest ---------------------------------------------------


def load_tenant_manifest(path) -> tuple[list[TenantSpec], dict]:
    """Parse a ``deploy --multi`` tenants manifest::

        {
          "memoryBudgetBytes": 2e9,          // optional, 0/absent = off
          "experimentSalt": "exp-2026w31",   // optional
          "defaultQuotaQps": 500,            // optional per-tenant default
          "evalIntervalSec": 5,              // optional online-eval cadence
          "tenants": [
            {"app": "shop", "variant": "control", "engineJson": "a/engine.json",
             "weight": 0.5, "pinned": true, "quotaQps": 200,
             "engineInstanceId": null, "accessKey": null}
          ]
        }

    Returns ``(specs, options)``.  ``engineJson`` strings pass through
    VERBATIM: the string doubles as the engine-variant key the trained
    instance was registered under (`run_train(engine_variant=...)`),
    so it must equal what was passed to ``pio-tpu train`` — exactly
    the single-tenant ``--engine-json`` contract.  Relative paths
    therefore resolve against the deploy cwd, like every other CLI
    engine.json.  A tenant may instead carry ``"engine": "<name>"``
    naming a pio-forge REGISTERED engine (``pio-tpu engines list``);
    its instance resolves under the ``engine:<name>`` variant key
    (`train --engine <name>`)."""
    p = Path(path)
    doc = json.loads(p.read_text())
    tenants = doc.get("tenants")
    if not tenants:
        raise ValueError(f"{p}: manifest has no tenants")
    specs = []
    for t in tenants:
        ej = t.get("engineJson")
        specs.append(TenantSpec(
            app=t.get("app", ""),
            variant=t.get("variant", "default"),
            engine_json=ej,
            engine_name=t.get("engine"),
            instance_id=t.get("engineInstanceId"),
            access_key=t.get("accessKey"),
            weight=float(t.get("weight", 1.0)),
            pinned=bool(t.get("pinned", False)),
            quota_qps=t.get("quotaQps"),
            quota_burst=t.get("quotaBurst"),
        ))
    options = {
        "memory_budget_bytes": doc.get("memoryBudgetBytes"),
        "salt": doc.get("experimentSalt", "pio-hive"),
        "default_quota_qps": doc.get("defaultQuotaQps"),
        "eval_interval_s": float(doc.get("evalIntervalSec", 5.0)),
        # pio-pilot: {"autopilot": {"alpha": .., "minLift": ..}} (any
        # knob optional, presence alone enables the controller)
        "autopilot": doc.get("autopilot"),
    }
    return specs, options
