"""pio-hive: multi-tenant model serving with live A/B experimentation.

One serving process (or the pio-surge fleet) hosts many (app,
engine_variant) models behind a device-memory-budgeted
:class:`TenantRegistry` — lazy load, LRU eviction + pinning, per-tenant
circuit breakers / token-bucket quotas / metric labels — with weighted
sticky variant assignment, per-variant feedback attribution through the
event store, and an online-eval aggregator feeding ``/metrics`` and
pio-tower manifests.  See ``docs/ARCHITECTURE.md`` "Multi-tenancy".
"""

from .autopilot import (
    AutoPilot,
    AutopilotConfig,
    autopilot_payload,
    sprt_test,
    step_weights,
)
from .errors import QuotaExceeded, TenantUnavailable, UnknownTenant
from .experiment import Experiment, assign_bucket
from .online_eval import OnlineEval
from .quota import TokenBucket
from .registry import (
    TenantLease,
    TenantRegistry,
    TenantRuntime,
    TenantSpec,
    load_tenant_manifest,
    model_resident_bytes,
)

__all__ = [
    "AutoPilot",
    "AutopilotConfig",
    "Experiment",
    "OnlineEval",
    "QuotaExceeded",
    "TenantLease",
    "TenantRegistry",
    "TenantRuntime",
    "TenantSpec",
    "TenantUnavailable",
    "TokenBucket",
    "UnknownTenant",
    "assign_bucket",
    "autopilot_payload",
    "load_tenant_manifest",
    "model_resident_bytes",
    "sprt_test",
    "step_weights",
]
