"""Per-tenant token-bucket rate limiting.

One bucket per tenant: ``rate_qps`` tokens refill per second up to
``burst``; a query costs one token.  A tenant that exhausts its bucket
is answered a structured 429 (``QuotaExceeded``) at admission — before
any device work queues — so one tenant's traffic spike cannot convert
into another tenant's queue wait.  Pure monotonic-clock arithmetic, no
background thread.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

__all__ = ["TokenBucket"]


class TokenBucket:
    """Classic token bucket on the monotonic clock.

    ``clock`` is injectable so quota tests are deterministic (the same
    seam every resilience primitive in this repo exposes).
    """

    def __init__(self, rate_qps: float, burst: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        if rate_qps <= 0:
            raise ValueError(f"rate_qps must be > 0, got {rate_qps}")
        self.rate_qps = float(rate_qps)
        # default burst = one second of rate (min 1 so a sub-1-QPS
        # tenant can ever serve at all)
        self.burst = float(burst) if burst is not None else max(
            self.rate_qps, 1.0
        )
        if self.burst < 1.0:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = clock()
        self.acquired = 0
        self.rejected = 0

    def try_acquire(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; never blocks."""
        now = self._clock()
        with self._lock:
            elapsed = max(now - self._last, 0.0)
            self._last = now
            self._tokens = min(
                self.burst, self._tokens + elapsed * self.rate_qps
            )
            if self._tokens >= n:
                self._tokens -= n
                self.acquired += 1
                return True
            self.rejected += 1
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rateQps": self.rate_qps,
                "burst": self.burst,
                "tokens": round(self._tokens, 3),
                "acquired": self.acquired,
                "rejected": self.rejected,
            }
