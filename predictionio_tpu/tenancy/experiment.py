"""Weighted A/B variant assignment: sticky, deterministic, hot-updatable.

One :class:`Experiment` per app groups that app's engine variants.
Assignment is ``hash(salt, app, user) -> [0, 1)`` mapped onto the
cumulative weight intervals of the variants in sorted-name order:

* **Sticky across restarts**: the hash is salted SHA-256 — no process
  state, no assignment table to persist.  The same (salt, app, user)
  lands on the same variant on every replica of the fleet and after
  every redeploy, which is what makes per-user A/B attribution valid.
* **Deterministic under weight updates**: updating weights moves only
  the users whose hash falls in the shifted interval mass — roughly
  ``|w - w'|`` of traffic per variant — while everyone else stays put.
  Weights are hot-updatable through the admin API
  (``POST /tenants/weights``) without a restart; the router broadcasts
  the update fleet-wide so every replica assigns identically.
"""

from __future__ import annotations

import hashlib
import struct
import threading

__all__ = ["Experiment", "assign_bucket"]


def assign_bucket(salt: str, app: str, user: str) -> float:
    """Deterministic position in [0, 1) for a (salt, app, user) triple.
    First 8 bytes of SHA-256 — uniform enough that 10k users split
    within ~1% of the configured weights (property-tested)."""
    digest = hashlib.sha256(
        f"{salt}\x00{app}\x00{user}".encode("utf-8", "surrogatepass")
    ).digest()
    (v,) = struct.unpack(">Q", digest[:8])
    return v / 2.0 ** 64


class Experiment:
    """Weighted variant assignment for one app's engine variants."""

    def __init__(self, app: str, weights: dict[str, float],
                 salt: str = "pio-hive"):
        if not weights:
            raise ValueError(f"experiment for {app!r} needs >= 1 variant")
        for name, w in weights.items():
            if not (w >= 0.0):
                raise ValueError(
                    f"variant {name!r} weight must be >= 0, got {w}"
                )
        if sum(weights.values()) <= 0:
            raise ValueError(
                f"experiment for {app!r} needs positive total weight"
            )
        self.app = app
        self.salt = salt
        self._lock = threading.Lock()
        self._weights = dict(weights)
        self.updates = 0

    def variants(self) -> list[str]:
        with self._lock:
            return sorted(self._weights)

    def weights(self) -> dict[str, float]:
        with self._lock:
            return dict(self._weights)

    def set_weights(self, weights: dict[str, float]) -> None:
        """Hot-update some or all variant weights.  Unknown variant
        names refuse loudly (a typo must not silently route 0 traffic),
        and the surviving total must stay positive."""
        with self._lock:
            unknown = set(weights) - set(self._weights)
            if unknown:
                raise KeyError(
                    f"unknown variant(s) {sorted(unknown)} for app "
                    f"{self.app!r}; known: {sorted(self._weights)}"
                )
            merged = {**self._weights, **{
                k: float(v) for k, v in weights.items()
            }}
            for name, w in merged.items():
                if not (w >= 0.0):
                    raise ValueError(
                        f"variant {name!r} weight must be >= 0, got {w}"
                    )
            if sum(merged.values()) <= 0:
                raise ValueError(
                    f"weights for {self.app!r} would sum to 0"
                )
            self._weights = merged
            self.updates += 1

    def assign(self, user: str) -> str:
        """The user's sticky variant under the CURRENT weights.
        Variants walk in sorted-name order so the interval layout is
        reproducible from the weight dict alone."""
        r = assign_bucket(self.salt, self.app, str(user))
        with self._lock:
            items = sorted(self._weights.items())
            total = sum(w for _, w in items)
        acc = 0.0
        for name, w in items:
            acc += w / total
            if r < acc:
                return name
        return items[-1][0]  # float round-off on the last boundary

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "app": self.app,
                "salt": self.salt,
                "weights": dict(self._weights),
                "updates": self.updates,
            }
