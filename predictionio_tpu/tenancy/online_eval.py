"""Online (interleaved) evaluation: per-variant outcome aggregation.

The offline eval machinery scores candidates against held-out history;
the hive turns it ONLINE: every served query books an impression under
its (app, variant), the variant tag rides the feedback loop into the
event store (``_send_feedback`` stamps it; clients echo it on their
conversion events), and this aggregator scans the store's incremental
cursor (``find_rows_since`` — the same primitive pio-live folds in on)
to count variant-attributed conversions back out.

The result is a CTR-style table — ``rate = conversions / impressions``
per (app, variant) — exported three ways:

* ``pio_variant_requests_total`` / ``pio_variant_feedback_total`` /
  ``pio_variant_outcome_rate`` on ``/metrics``,
* the ``onlineEval`` block of ``GET /debug/tenants``,
* ``candidate`` records appended to a pio-tower run manifest
  (``$PIO_TPU_HOME/telemetry/runs/hive-online-<id>/run.jsonl``), so
  ``tools/runlog.py summarize`` reads an A/B the way it reads an eval
  sweep.

Impressions are in-process counters (the serving edge books them at
serve time); conversions come from the store scan, so a multi-replica
fleet's per-replica tables aggregate exactly like every other counter
family (pio-tower cluster merge).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Optional

from ..obs import (
    ONLINE_EVAL_CURSOR_LAG,
    VARIANT_FEEDBACK_TOTAL,
    VARIANT_RATE,
    VARIANT_REQUESTS_TOTAL,
)

__all__ = ["OnlineEval", "merge_cursor"]

logger = logging.getLogger(__name__)

# events that are impressions flowing back through the feedback loop,
# not client conversions — counting them would make every rate ~1.0
_FEEDBACK_EVENT = "predict"


def merge_cursor(old, new):
    """Component-wise monotone merge of two store cursors (the PR 13
    vector-cursor algebra).  A sharded scan run with
    ``tolerate_unavailable=True`` while a shard owner is mid-death can
    hand back a component BEHIND what an earlier scan already covered;
    adopting it verbatim would re-scan (and double-count) conversions.
    Int cursors take the max; JSON shard-vector strings merge per
    component over the union of shard keys.  Unparseable inputs fall
    back to ``new`` (never block the scan on cursor cosmetics)."""
    if old is None:
        return new
    if isinstance(old, int) and isinstance(new, int):
        return max(old, new)
    try:
        ov = json.loads(old) if isinstance(old, str) else old
        nv = json.loads(new) if isinstance(new, str) else new
        if isinstance(ov, dict) and isinstance(nv, dict):
            merged = {
                k: max(int(ov.get(k, 0)), int(nv.get(k, 0)))
                for k in set(ov) | set(nv)
            }
            return json.dumps(
                {k: merged[k] for k in sorted(merged, key=int)}
            )
        if isinstance(ov, int) and isinstance(nv, int):
            return max(ov, nv)
    except (ValueError, TypeError):
        pass
    return new


class OnlineEval:
    def __init__(self, salt: str = "pio-hive",
                 manifest_id: Optional[str] = None,
                 scan_page: int = 5000):
        self._lock = threading.Lock()
        # (app, variant) -> {"impressions": n, "conversions": n}
        self._stats: dict[tuple[str, str], dict] = {}
        # app -> opaque store cursor (int for the single-file store,
        # JSON shard-vector string for the sharded store — passed back
        # verbatim, never interpreted here)
        self._cursors: dict[str, object] = {}
        self.salt = salt
        self.scan_page = scan_page
        self.manifest_id = manifest_id or f"hive-online-{uuid.uuid4().hex[:8]}"
        self._manifest = None
        self.refreshes = 0

    def _cell(self, app: str, variant: str) -> dict:
        key = (app, variant)
        cell = self._stats.get(key)
        if cell is None:
            cell = {"impressions": 0, "conversions": 0}
            self._stats[key] = cell
        return cell

    def impression(self, app: str, variant: str) -> None:
        with self._lock:
            self._cell(app, variant)["impressions"] += 1
        VARIANT_REQUESTS_TOTAL.labels(app=app, variant=variant).inc()

    # -- conversion scan ---------------------------------------------------
    def refresh(self, event_store, app_ids: dict[str, int]) -> dict:
        """Scan each app's store past its cursor for variant-attributed
        conversion events, update rates, and append the table to the
        tower manifest.  Returns :meth:`snapshot`.  Store errors are
        logged and skipped — online eval must never fail serving."""
        for app, app_id in sorted(app_ids.items()):
            if not hasattr(event_store, "find_rows_since"):
                break
            with self._lock:
                cursor = self._cursors.get(app, 0)
            # pio-levee: on a sharded store, tolerate a down shard —
            # its vector-cursor component freezes (resuming without
            # loss when its owner returns) while healthy shards keep
            # feeding conversions
            kw = (
                {"tolerate_unavailable": True}
                if hasattr(event_store, "shards") else {}
            )
            try:
                rows, new_cursor = event_store.find_rows_since(
                    app_id, 0, cursor=cursor, limit=self.scan_page, **kw,
                )
            except Exception:
                logger.exception("online-eval scan failed for app %s", app)
                continue
            counted: dict[str, int] = {}
            for r in rows:
                # r = (rowid, event_id, event, entity_type, entity_id,
                #      tet, tei, properties, event_time, tags, pr_id,
                #      creation_time)
                if r[2] == _FEEDBACK_EVENT:
                    continue
                try:
                    variant = json.loads(r[7]).get("variant")
                except (json.JSONDecodeError, TypeError):
                    continue
                if variant:
                    counted[str(variant)] = counted.get(
                        str(variant), 0
                    ) + 1
            with self._lock:
                # component-wise monotone: a tolerated-unavailable scan
                # must never walk a shard component backward (that
                # would re-count its conversions when it returns)
                self._cursors[app] = merge_cursor(
                    self._cursors.get(app), new_cursor,
                )
                merged = self._cursors[app]
                for variant, n in counted.items():
                    self._cell(app, variant)["conversions"] += n
            for variant, n in counted.items():
                VARIANT_FEEDBACK_TOTAL.labels(
                    app=app, variant=variant
                ).inc(n)
            if hasattr(event_store, "cursor_lag"):
                try:
                    ONLINE_EVAL_CURSOR_LAG.labels(app=app).set(
                        float(event_store.cursor_lag(app_id, 0, merged))
                    )
                except Exception:
                    logger.debug(
                        "cursor-lag probe failed for app %s", app,
                        exc_info=True,
                    )
        snap = self.snapshot()
        self._export(snap)
        return snap

    def _export(self, snap: dict) -> None:
        """Gauges + one manifest record per (app, variant)."""
        with self._lock:
            self.refreshes += 1
            refresh_ix = self.refreshes
        for key, cell in snap.items():
            app, _, variant = key.partition("/")
            VARIANT_RATE.labels(app=app, variant=variant).set(
                cell["rate"]
            )
        manifest = self._ensure_manifest()
        if manifest is None:
            return
        for key, cell in sorted(snap.items()):
            app, _, variant = key.partition("/")
            manifest.candidate(
                refresh_ix, app=app, variant=variant,
                impressions=cell["impressions"],
                conversions=cell["conversions"],
                rate=cell["rate"],
            )

    def _ensure_manifest(self):
        if self._manifest is None:
            try:
                from ..obs.runlog import RunManifest

                self._manifest = RunManifest(
                    self.manifest_id, kind="online_eval",
                    meta={"salt": self.salt, "startedAt": time.time()},
                )
            except Exception:
                logger.exception("online-eval manifest unavailable")
                return None
        return self._manifest

    def snapshot(self) -> dict:
        with self._lock:
            return {
                f"{app}/{variant}": {
                    "impressions": cell["impressions"],
                    "conversions": cell["conversions"],
                    "rate": (
                        round(cell["conversions"]
                              / cell["impressions"], 6)
                        if cell["impressions"] else 0.0
                    ),
                }
                for (app, variant), cell in sorted(self._stats.items())
            }

    def close(self) -> None:
        with self._lock:
            refreshes = self.refreshes
        m = self._manifest
        if m is not None:
            m.finalize("completed", refreshes=refreshes)
