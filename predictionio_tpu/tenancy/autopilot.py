"""pio-pilot autopilot: self-driving experiments.

The hive's online eval keeps a per-(app, variant) conversion table
fresh (``online_eval.py``); this module closes the loop so an A/B
concludes ITSELF instead of waiting for a human to read
``pio_variant_outcome_rate``:

* **SPRT** — Wald's sequential probability-ratio test over the
  Bernoulli conversion stream.  Per tick the controller recomputes the
  log-likelihood-ratio walk for the provisional leader against a
  plug-in null (the best challenger's observed rate, Laplace-smoothed)
  vs that rate lifted by ``min_lift``, and compares it to the
  ``log((1-beta)/alpha)`` / ``log(beta/(1-alpha))`` thresholds.  A
  ``min_samples`` floor on BOTH variants gates the walk — no decision
  fires off ten lucky conversions.
* **Guardrail** — a fast-but-broken variant can never win: any variant
  whose tenant breaker is not closed, or whose serving error ratio
  crosses ``error_ratio``, is vetoed from leadership and ramped DOWN;
  a fleet-level ``pio_slo_burn_rate`` breach freezes all ramping (the
  experiment keeps collecting, traffic stops moving).
* **Bounded ramp** — traffic moves toward the winner at most
  ``max_step`` weight per tick and every loser keeps ``min_weight``
  (never zeroed: the holdout keeps measuring, and a mistaken ramp is
  reversible).  Weight application goes through an injectable
  ``apply_weights`` callable — in-process ``registry.set_weights`` by
  default, the real ``POST /tenants/weights`` router broadcast when
  the serving edge wires it.

Every decision (ramp / veto / conclude / hold) is written as a
pio-tower manifest event (``kind="autopilot"``) and surfaced at
``GET /debug/experiments`` + the dashboard's ``experiments.html``.
"""

from __future__ import annotations

import logging
import math
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from ..obs import (
    BREAKER_STATE_VALUES,
    EXPERIMENT_DECISIONS_TOTAL,
    EXPERIMENT_LLR,
    EXPERIMENT_STATE,
    TENANT_QUERIES_TOTAL,
)

__all__ = [
    "AutoPilot",
    "AutopilotConfig",
    "SprtResult",
    "autopilot_payload",
    "set_autopilot",
    "sprt_llr",
    "sprt_test",
    "step_weights",
]

logger = logging.getLogger(__name__)

# EXPERIMENT_STATE gauge encoding
STATE_COLLECTING = 0.0
STATE_RAMPING = 1.0
STATE_CONCLUDED = 2.0
STATE_FROZEN = 3.0

_EPS = 1e-9
_P_CLAMP = 1e-6


@dataclass(frozen=True)
class AutopilotConfig:
    # SPRT error bounds: alpha = P(accept lift | none), beta = P(miss
    # a real lift)
    alpha: float = 0.05
    beta: float = 0.20
    # the lift worth detecting: H1 puts the leader at
    # challenger_rate * (1 + min_lift)
    min_lift: float = 0.20
    # both leader and challenger need this many impressions before the
    # walk can conclude anything
    min_samples: int = 200
    # ramp bounds: at most max_step weight moves per tick, and every
    # variant keeps min_weight (the loser is ramped down, never zeroed)
    max_step: float = 0.10
    min_weight: float = 0.05
    # guardrails: freeze all ramping when any pio_slo_burn_rate window
    # exceeds burn_threshold; veto a variant whose error ratio (over
    # its tenant-serving outcomes) crosses error_ratio with at least
    # min_errors failures, or whose breaker is not closed
    burn_threshold: float = 1.0
    error_ratio: float = 0.5
    min_errors: int = 5

    def __post_init__(self) -> None:
        if not 0 < self.alpha < 1 or not 0 < self.beta < 1:
            raise ValueError("alpha/beta must be in (0, 1)")
        if self.min_lift <= 0:
            raise ValueError("minLift must be > 0")
        if not 0 < self.max_step <= 1:
            raise ValueError("maxStep must be in (0, 1]")
        if not 0 <= self.min_weight < 0.5:
            raise ValueError("minWeight must be in [0, 0.5)")

    @classmethod
    def from_doc(cls, doc: dict) -> "AutopilotConfig":
        """Manifest/JSON knobs (camelCase, all optional)."""
        aliases = {
            "alpha": "alpha", "beta": "beta", "minLift": "min_lift",
            "minSamples": "min_samples", "maxStep": "max_step",
            "minWeight": "min_weight",
            "burnThreshold": "burn_threshold",
            "errorRatio": "error_ratio", "minErrors": "min_errors",
        }
        kw = {}
        for k, v in (doc or {}).items():
            field = aliases.get(k, k)
            if field in cls.__dataclass_fields__:
                kw[field] = type(cls.__dataclass_fields__[field].default)(v)
        return cls(**kw)


# -- SPRT core (pure math, directly unit-testable) --------------------------


def sprt_llr(n: int, c: int, p0: float, p1: float) -> float:
    """Wald log-likelihood ratio after ``n`` Bernoulli trials with
    ``c`` successes, H1: p = p1 vs H0: p = p0."""
    p0 = min(max(p0, _P_CLAMP), 1.0 - _P_CLAMP)
    p1 = min(max(p1, _P_CLAMP), 1.0 - _P_CLAMP)
    return (c * math.log(p1 / p0)
            + (n - c) * math.log((1.0 - p1) / (1.0 - p0)))


@dataclass(frozen=True)
class SprtResult:
    decision: str  # "accept_h1" | "accept_h0" | "continue"
    llr: float
    upper: float
    lower: float


def sprt_test(n: int, c: int, p0: float, p1: float,
              alpha: float = 0.05, beta: float = 0.20) -> SprtResult:
    """One SPRT verdict from cumulative counts.  The walk is
    recomputed closed-form every tick (the plug-in null may move as
    the challenger's rate converges), which keeps the controller
    stateless across restarts."""
    upper = math.log((1.0 - beta) / alpha)
    lower = math.log(beta / (1.0 - alpha))
    llr = sprt_llr(n, c, p0, p1)
    if llr >= upper:
        decision = "accept_h1"
    elif llr <= lower:
        decision = "accept_h0"
    else:
        decision = "continue"
    return SprtResult(decision=decision, llr=llr, upper=upper,
                      lower=lower)


def step_weights(weights: dict[str, float], toward: str,
                 max_step: float, min_weight: float,
                 only_from: Optional[set[str]] = None
                 ) -> dict[str, float]:
    """One bounded ramp step: move at most ``max_step`` of the
    (normalized) traffic mass toward ``toward``, taken proportionally
    from the other variants' headroom above ``min_weight`` (or only
    from ``only_from`` when set — the veto ramp-down).  Total mass is
    preserved, no variant drops below ``min_weight``, and when nothing
    can move the input comes back unchanged (the minimal-move
    contract: only |w - w'| traffic re-assigns, per Experiment's
    sticky-interval layout)."""
    total = sum(weights.values())
    if toward not in weights or total <= 0:
        return dict(weights)
    norm = {k: v / total for k, v in weights.items()}
    donors = {
        k: max(norm[k] - min_weight, 0.0)
        for k in norm
        if k != toward and (only_from is None or k in only_from)
    }
    headroom = sum(donors.values())
    take = min(max_step, headroom)
    if take <= _EPS:
        return dict(weights)
    out = dict(norm)
    for k, h in donors.items():
        out[k] -= take * (h / headroom)
    out[toward] += take
    return {k: round(v, 9) for k, v in out.items()}


# -- the controller ----------------------------------------------------------


class AutoPilot:
    """Per-app experiment controller over a :class:`TenantRegistry`.

    ``tick()`` is driven by the serving edge's online-eval loop (or a
    test/smoke harness); it reads the registry's online-eval table and
    live experiment weights, runs guardrails + SPRT, and applies at
    most one bounded weight step per app via ``apply_weights``.
    """

    def __init__(self, registry, config: Optional[AutopilotConfig] = None,
                 apply_weights: Optional[Callable[[str, dict], object]] = None,
                 manifest_id: Optional[str] = None,
                 burn_rate_fn: Optional[Callable[[], float]] = None):
        self.registry = registry
        self.config = config or AutopilotConfig()
        self._apply = apply_weights or (
            lambda app, weights: registry.set_weights(app, weights)
        )
        self.manifest_id = (
            manifest_id or f"pilot-{uuid.uuid4().hex[:8]}"
        )
        self._manifest = None
        self._burn_rate_fn = burn_rate_fn or self._max_burn_rate
        self._lock = threading.Lock()
        # app -> {"state": float, "last": dict, "decisions": [..tail]}
        self._apps: dict[str, dict] = {}
        self.ticks = 0

    # -- guardrail inputs --------------------------------------------------
    @staticmethod
    def _max_burn_rate() -> float:
        """Worst window of the fleet's pio_slo_burn_rate gauges (0.0
        when the SLO tracker isn't installed)."""
        try:
            from ..obs.fleet import SLO_BURN_RATE

            worst = 0.0
            for _labels, child in SLO_BURN_RATE.children():
                v = child.value()
                if not math.isnan(v):
                    worst = max(worst, v)
            return worst
        except Exception:
            return 0.0

    def _breaker_state(self, app: str, variant: str) -> str:
        try:
            rt = self.registry._runtimes.get((app, variant))
        except AttributeError:
            rt = None
        if rt is None:
            return "closed"
        return rt.breaker.state

    def _error_counts(self, app: str, variant: str) -> tuple[float, float]:
        """(failures, total) from the per-tenant serving outcome
        counters — the client-visible evidence a variant is broken."""
        total = 0.0
        failures = 0.0
        for labels, child in TENANT_QUERIES_TOTAL.children():
            kv = dict(labels)
            if kv.get("app") != app or kv.get("variant") != variant:
                continue
            v = child.value()
            total += v
            if kv.get("status") in ("error", "timeout", "rejected"):
                failures += v
        return failures, total

    def _veto_reason(self, app: str, variant: str) -> Optional[str]:
        breaker = self._breaker_state(app, variant)
        if BREAKER_STATE_VALUES.get(breaker, 0.0) > 0.0:
            return f"breaker_{breaker.replace('-', '_')}"
        failures, total = self._error_counts(app, variant)
        if (failures >= self.config.min_errors and total > 0
                and failures / total >= self.config.error_ratio):
            return "error_ratio"
        return None

    # -- one controller pass ----------------------------------------------
    def tick(self) -> dict:
        """Run guardrails + SPRT + at most one ramp step per app;
        returns :meth:`payload`.  Never raises — a broken tick must
        not take down the serving loop that drives it."""
        try:
            snap = self.registry.online.snapshot()
            apps = self.registry.apps()
        except Exception:
            logger.exception("autopilot tick: registry unavailable")
            return self.payload()
        burn = self._burn_rate_fn()
        for app in apps:
            try:
                self._tick_app(app, snap, burn)
            except Exception:
                logger.exception("autopilot tick failed for app %s", app)
        with self._lock:
            self.ticks += 1
        return self.payload()

    def _tick_app(self, app: str, snap: dict, burn: float) -> None:
        cfg = self.config
        try:
            weights = self.registry.experiment(app).weights()
        except Exception:
            return
        if len(weights) < 2:
            return
        stats = {}
        for variant in weights:
            cell = snap.get(f"{app}/{variant}", {})
            stats[variant] = {
                "impressions": int(cell.get("impressions", 0)),
                "conversions": int(cell.get("conversions", 0)),
                "rate": float(cell.get("rate", 0.0)),
            }
        vetoes = {
            v: reason for v in sorted(weights)
            if (reason := self._veto_reason(app, v)) is not None
        }

        frozen = burn > cfg.burn_threshold
        if frozen:
            self._decide(
                app, "hold", state=STATE_FROZEN, stats=stats,
                weights=weights, vetoes=vetoes, burn=burn,
                reason="burn_rate",
            )
            return

        eligible = [v for v in sorted(weights) if v not in vetoes]
        new_weights = None
        # a vetoed variant holding traffic is ramped down first —
        # safety moves outrank significance moves
        if vetoes and eligible:
            total = sum(weights.values()) or 1.0
            over = {
                v: weights[v] / total - cfg.min_weight
                for v in vetoes
            }
            if max(over.values()) > 1e-6:
                target = max(
                    eligible,
                    key=lambda v: (stats[v]["rate"], v),
                )
                new_weights = step_weights(
                    weights, target, cfg.max_step, cfg.min_weight,
                    only_from=set(vetoes),
                )
                self._apply_weights(app, new_weights)
                self._decide(
                    app, "veto", state=STATE_RAMPING, stats=stats,
                    weights=new_weights, vetoes=vetoes, burn=burn,
                    reason=";".join(
                        f"{v}:{r}" for v, r in sorted(vetoes.items())
                    ),
                    target=target,
                )
                return

        if len(eligible) < 2:
            self._decide(
                app, "hold", state=STATE_COLLECTING, stats=stats,
                weights=weights, vetoes=vetoes, burn=burn,
                reason="single_variant" if vetoes else "no_variants",
            )
            return

        ranked = sorted(
            eligible, key=lambda v: (stats[v]["rate"], v), reverse=True,
        )
        leader, challenger = ranked[0], ranked[1]
        ln, lc = (stats[leader]["impressions"],
                  stats[leader]["conversions"])
        cn, cc = (stats[challenger]["impressions"],
                  stats[challenger]["conversions"])
        if min(ln, cn) < cfg.min_samples:
            self._decide(
                app, "hold", state=STATE_COLLECTING, stats=stats,
                weights=weights, vetoes=vetoes, burn=burn,
                reason="min_samples", leader=leader,
                challenger=challenger,
            )
            return

        # plug-in null: the challenger's Laplace-smoothed rate; H1
        # lifts it by min_lift
        p0 = (cc + 1.0) / (cn + 2.0)
        p1 = min(p0 * (1.0 + cfg.min_lift), 1.0 - _P_CLAMP)
        res = sprt_test(ln, lc, p0, p1, alpha=cfg.alpha, beta=cfg.beta)
        EXPERIMENT_LLR.labels(app=app, variant=leader).set(res.llr)

        if res.decision == "accept_h1":
            new_weights = step_weights(
                weights, leader, cfg.max_step, cfg.min_weight,
            )
            moved = any(
                abs(new_weights[v]
                    - weights[v] / (sum(weights.values()) or 1.0))
                > 1e-6
                for v in weights
            )
            if moved:
                self._apply_weights(app, new_weights)
                self._decide(
                    app, "ramp", state=STATE_RAMPING, stats=stats,
                    weights=new_weights, vetoes=vetoes, burn=burn,
                    leader=leader, challenger=challenger, sprt=res,
                )
            else:
                # the winner already holds every ramp-able point —
                # the experiment has concluded itself
                self._decide(
                    app, "conclude", state=STATE_CONCLUDED,
                    stats=stats, weights=weights, vetoes=vetoes,
                    burn=burn, leader=leader, challenger=challenger,
                    sprt=res,
                )
        elif res.decision == "accept_h0":
            self._decide(
                app, "hold", state=STATE_COLLECTING, stats=stats,
                weights=weights, vetoes=vetoes, burn=burn,
                reason="no_lift", leader=leader,
                challenger=challenger, sprt=res,
            )
        else:
            self._decide(
                app, "hold", state=STATE_COLLECTING, stats=stats,
                weights=weights, vetoes=vetoes, burn=burn,
                reason="collecting", leader=leader,
                challenger=challenger, sprt=res,
            )

    def _apply_weights(self, app: str, weights: dict) -> None:
        try:
            self._apply(app, weights)
        except Exception:
            logger.exception(
                "autopilot weight update failed for app %s", app
            )

    # -- bookkeeping -------------------------------------------------------
    def _decide(self, app: str, decision: str, *, state: float,
                stats: dict, weights: dict, vetoes: dict, burn: float,
                reason: Optional[str] = None,
                leader: Optional[str] = None,
                challenger: Optional[str] = None,
                target: Optional[str] = None,
                sprt: Optional[SprtResult] = None) -> None:
        EXPERIMENT_DECISIONS_TOTAL.labels(app=app, decision=decision).inc()
        EXPERIMENT_STATE.labels(app=app).set(state)
        record = {
            "at": time.time(),
            "decision": decision,
            "state": state,
            "reason": reason,
            "leader": leader,
            "challenger": challenger,
            "target": target,
            "weights": dict(weights),
            "vetoes": dict(vetoes),
            "burnRate": round(burn, 6),
            "stats": stats,
        }
        if sprt is not None:
            record["llr"] = round(sprt.llr, 6)
            record["upper"] = round(sprt.upper, 6)
            record["lower"] = round(sprt.lower, 6)
        with self._lock:
            cell = self._apps.setdefault(
                app, {"state": STATE_COLLECTING, "decisions": []}
            )
            # a concluded experiment stays concluded (the gauge keeps
            # reporting 2 even while holds keep streaming)
            if cell["state"] != STATE_CONCLUDED or decision in (
                "ramp", "conclude", "veto",
            ):
                cell["state"] = state
            cell["last"] = record
            cell["decisions"].append(record)
            del cell["decisions"][:-50]
            sticky_state = cell["state"]
        if sticky_state == STATE_CONCLUDED:
            EXPERIMENT_STATE.labels(app=app).set(STATE_CONCLUDED)
        manifest = self._ensure_manifest()
        if manifest is not None:
            manifest.event(
                "decision", app=app,
                **{k: v for k, v in record.items() if k != "at"},
            )

    def _ensure_manifest(self):
        if self._manifest is None:
            try:
                from ..obs.runlog import RunManifest

                self._manifest = RunManifest(
                    self.manifest_id, kind="autopilot",
                    meta={
                        "alpha": self.config.alpha,
                        "beta": self.config.beta,
                        "minLift": self.config.min_lift,
                        "minSamples": self.config.min_samples,
                        "maxStep": self.config.max_step,
                        "minWeight": self.config.min_weight,
                        "startedAt": time.time(),
                    },
                )
            except Exception:
                logger.exception("autopilot manifest unavailable")
                return None
        return self._manifest

    def payload(self) -> dict:
        """The ``GET /debug/experiments`` document."""
        with self._lock:
            apps = {
                app: {
                    "state": cell["state"],
                    "stateName": _state_name(cell["state"]),
                    "last": cell.get("last"),
                    "decisions": list(cell["decisions"][-10:]),
                }
                for app, cell in sorted(self._apps.items())
            }
            ticks = self.ticks
        try:
            weights = {
                app: self.registry.experiment(app).weights()
                for app in self.registry.apps()
            }
        except Exception:
            weights = {}
        return {
            "enabled": True,
            "manifestId": self.manifest_id,
            "ticks": ticks,
            "config": {
                "alpha": self.config.alpha,
                "beta": self.config.beta,
                "minLift": self.config.min_lift,
                "minSamples": self.config.min_samples,
                "maxStep": self.config.max_step,
                "minWeight": self.config.min_weight,
                "burnThreshold": self.config.burn_threshold,
            },
            "weights": weights,
            "apps": apps,
        }

    def close(self) -> None:
        m = self._manifest
        if m is not None:
            with self._lock:
                ticks = self.ticks
            m.finalize("completed", ticks=ticks)


def _state_name(state: float) -> str:
    return {
        STATE_COLLECTING: "collecting",
        STATE_RAMPING: "ramping",
        STATE_CONCLUDED: "concluded",
        STATE_FROZEN: "frozen",
    }.get(state, "unknown")


# -- module-level hook (the fleet_payload pattern): the serving edge and
# the dashboard read whichever autopilot this process installed -------------

_current: Optional[AutoPilot] = None


def set_autopilot(pilot: Optional[AutoPilot]) -> None:
    global _current
    _current = pilot


def autopilot_payload() -> Optional[dict]:
    pilot = _current
    if pilot is None:
        return None
    try:
        return pilot.payload()
    except Exception:
        logger.exception("autopilot payload failed")
        return None
