"""Tenancy error types, importable without the registry machinery.

Kept in their own module so ``server/serving.py`` can import the
classes for isinstance mapping without pulling the whole tenancy
package into its import graph (the registry's loader lives in serving —
a top-level cross-import would cycle).

Both subclass :class:`~predictionio_tpu.resilience.policy.DeadlineExceeded`
so code that only knows the resilience taxonomy (retry loops, generic
503 mapping) treats a shed tenant exactly like any other structured
overload answer; the serving edges additionally map each to its own
error name and status code (429 for quota, 503 for unavailability).
"""

from __future__ import annotations

from ..resilience.policy import DeadlineExceeded

__all__ = ["QuotaExceeded", "TenantUnavailable", "UnknownTenant"]


class QuotaExceeded(DeadlineExceeded):
    """The tenant's token-bucket rate limit is exhausted (HTTP 429)."""


class TenantUnavailable(DeadlineExceeded):
    """The tenant cannot serve right now: its breaker is open (repeated
    errors/timeouts — the isolation shed) or its lazy load failed.
    The rest of the process keeps serving every other tenant."""


class UnknownTenant(KeyError):
    """The query named an (app, variant) or access key no tenant spec
    covers — a client error (HTTP 400), never a server fault."""
