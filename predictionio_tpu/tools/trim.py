"""Trim old events from an app's event store.

Capability analogue of the reference's
`examples/experimental/scala-parallel-trim-app` (a Spark job that rewrote
an app's events minus a time window); here it's a streaming
find-and-delete over the embedded store, promoted from example to a
first-class `pio-tpu app trim` command.
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional, Sequence

from ..storage.levents import EventStore

__all__ = ["trim_events"]


def trim_events(
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
    before: Optional[_dt.datetime] = None,
    event_names: Optional[Sequence[str]] = None,
    keep_special: bool = True,
    batch: int = 5000,
) -> int:
    """Delete events older than ``before`` (and/or matching
    ``event_names``); returns the number deleted.

    ``keep_special`` preserves ``$set/$unset/$delete`` property events so
    entity snapshots survive the trim (the reference example kept them
    for the same reason).
    """
    if before is None and not event_names:
        raise ValueError(
            "trim requires a time window (before=...) and/or event names; "
            "use data-delete to drop everything"
        )
    # collect ids first, then delete: interleaving deletes with a live
    # find() cursor is undefined on cursor-backed stores
    to_delete = [
        e.event_id
        for e in store.find(
            app_id=app_id, channel_id=channel_id, until_time=before,
            event_names=list(event_names) if event_names else None,
        )
        if e.event_id and not (keep_special and e.event.startswith("$"))
    ]
    n = 0
    for s in range(0, len(to_delete), batch):
        n += store.delete_batch(to_delete[s : s + batch], app_id, channel_id)
    return n
