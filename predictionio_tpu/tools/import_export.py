"""Event export/import: JSON-lines files <-> event store.

Parity with reference `tools/export/EventsToFile.scala:30-104` (JSON output;
the Parquet variant is out of scope for an embedded store) and
`tools/imprt/FileToEvents.scala:30-95`.  The reference runs these as Spark
jobs; here they are streaming host loops over the embedded store with
batched inserts.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

from ..storage.event import DataMap, Event
from ..storage.levents import EventStore

__all__ = ["import_events", "export_events", "import_ratings_csv"]

_BATCH = 5000


def import_events(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
) -> int:
    """JSON-lines file -> event store; returns number imported."""
    n = 0
    batch: list[Event] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            batch.append(Event.from_json(json.loads(line)))
            if len(batch) >= _BATCH:
                store.insert_batch(batch, app_id, channel_id)
                n += len(batch)
                batch = []
    if batch:
        store.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    return n


def export_events(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
) -> int:
    """Event store -> JSON-lines file; returns number exported."""
    n = 0
    with open(path, "w") as f:
        for e in store.find(app_id=app_id, channel_id=channel_id):
            f.write(json.dumps(e.to_json(), separators=(",", ":")))
            f.write("\n")
            n += 1
    return n


def import_ratings_csv(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
    event: str = "rate",
    delimiter: str = "::",
    has_header: bool = False,
) -> int:
    """MovieLens-style ratings file (user<delim>item<delim>rating[...]) ->
    rate events — the quickstart data-import path of the recommendation
    template."""
    n = 0
    batch: list[Event] = []
    with open(path) as f:
        if has_header:
            next(f, None)
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split(delimiter)
            u, i, r = parts[0], parts[1], float(parts[2])
            batch.append(
                Event(
                    event=event,
                    entity_type="user",
                    entity_id=u,
                    target_entity_type="item",
                    target_entity_id=i,
                    properties=DataMap({"rating": r}),
                )
            )
            if len(batch) >= _BATCH:
                store.insert_batch(batch, app_id, channel_id)
                n += len(batch)
                batch = []
    if batch:
        store.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    return n
