"""Event export/import: JSON-lines files <-> event store.

Parity with reference `tools/export/EventsToFile.scala:30-104` (JSON
lines and, via pyarrow, the reference's SparkSQL-Parquet option) and
`tools/imprt/FileToEvents.scala:30-95`.  The reference runs these as
Spark jobs; here they are streaming host loops over the embedded store
with batched inserts.  Formats are inferred from the file extension or
content magic (`infer_format`), so any of JSON-lines / columnar npz /
Parquet round-trip through the same two entry points.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional  # noqa: F401 — used in signatures

from ..storage.event import DataMap, Event
from ..storage.levents import EventStore

__all__ = [
    "import_events",
    "import_events_columnar",
    "export_events",
    "columnar_path",
    "infer_format",
    "import_ratings_csv",
]


def infer_format(path: str | Path, default: str = "json") -> str:
    """File format from extension, else content magic, else ``default``.

    One inference shared by the CLI and both library entry points, so a
    Parquet file under any name (PAR1 magic) or an npz under any name
    (zip magic) is recognized everywhere.
    """
    p = str(path)
    if p.endswith(".npz"):
        return "columnar"
    if p.endswith(".parquet"):
        return "parquet"
    try:
        with open(p, "rb") as f:
            magic = f.read(4)
        if magic == b"PAR1":
            return "parquet"
        if magic[:2] == b"PK":
            return "columnar"
    except OSError:
        pass
    return default

_BATCH = 5000


def import_events(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
) -> int:
    """JSON-lines file -> event store; returns number imported.

    Bulk fast path: ``Event.from_json`` already validates, so batches are
    inserted with ``validate=False`` (no second validation pass), and the
    whole import runs in one ``store.bulk()`` scope (transactional
    backends commit once at the end, not per batch).
    """
    fmt = infer_format(path)
    if fmt == "parquet":
        return _import_parquet(path, store, app_id, channel_id)
    if fmt == "columnar":
        return import_events_columnar(path, store, app_id, channel_id)
    # table DDL before the transaction scope: sqlite auto-commits DDL,
    # which would break the all-or-nothing rollback guarantee
    store.init_channel(app_id, channel_id)
    if hasattr(store, "insert_raw_rows"):
        n = _import_events_native(path, store, app_id, channel_id)
        if n is not None:
            return n
    n = 0
    batch: list[Event] = []
    with open(path, encoding="utf-8") as f, store.bulk():
        for line in f:
            line = line.strip()
            if not line:
                continue
            batch.append(Event.from_json(json.loads(line)))
            if len(batch) >= _BATCH:
                store.insert_batch(batch, app_id, channel_id,
                                   validate=False)
                n += len(batch)
                batch = []
        if batch:
            store.insert_batch(batch, app_id, channel_id, validate=False)
            n += len(batch)
    return n


def _import_events_native(
    path: str | Path, store: EventStore, app_id: int, channel_id: int
) -> Optional[int]:
    """C++-scanned import fast path; None when the native lib is absent.

    ``native/jsonl_scan.cpp`` extracts each event's storage-row fields
    (and the raw ``properties`` substring, stored as-is — readers parse
    JSON text, so non-canonical spacing/ordering is semantically
    identical) in one pass.  Lines the scanner marks ``status=1`` —
    escapes, tags, validation failures, unusual timestamps — are
    re-parsed with the exact ``Event.from_json`` Python path, so errors
    and edge semantics match the portable importer byte for byte.
    Events without an eventTime get ONE shared import-time default
    rather than per-event ``now()`` calls.

    The file is scanned in bounded chunks (``_NATIVE_CHUNK`` bytes, split
    at line boundaries on the Python side) so peak memory stays flat at
    GB-file scale instead of holding the whole buffer plus full per-line
    offset arrays at once; all chunks flush inside ONE ``store.bulk()``
    scope, so transactional semantics are unchanged.
    """
    from ..native import scan_events_jsonl
    from ..storage.event import now_utc, time_millis

    if not native_scanner_available():
        return None
    now_ms = time_millis(now_utc())
    imported = 0
    with open(path, "rb") as fh, store.bulk():
        leftover = b""
        while True:
            block = fh.read(_NATIVE_CHUNK)
            if not block:
                data, leftover = leftover, b""
            else:
                data = leftover + block
                nl = data.rfind(b"\n")
                if nl < 0:
                    # no complete line in the buffer yet: keep reading (a
                    # single line longer than the chunk size)
                    leftover = data
                    continue
                # the scanner would treat a truncated trailing line as a
                # whole line; split at the last newline and carry the rest
                leftover = data[nl + 1:]
                data = data[: nl + 1]
            if data:
                scan = scan_events_jsonl(data)
                if scan is None:  # native lib vanished mid-import
                    raise RuntimeError(
                        "native scanner became unavailable during import"
                    )
                imported += _flush_scanned(
                    data, scan, store, app_id, channel_id, now_ms
                )
            if not block:
                break
    return imported


# chunk size for the native import scan; bounds peak host memory at
# roughly chunk + its per-line offset arrays regardless of file size
_NATIVE_CHUNK = 64 << 20


def native_scanner_available() -> bool:
    from ..native import _load

    lib = _load()
    return lib is not None and hasattr(lib, "pio_scan_events_jsonl")


def _flush_scanned(
    data: bytes, scan, store, app_id: int, channel_id: int, now_ms: int
) -> int:
    """Insert one scanned chunk's events (raw rows + python fallbacks)."""
    import numpy as np

    from ..native import (
        F_ENTITY_ID, F_ENTITY_TYPE, F_EVENT, F_EVENT_ID, F_PR_ID,
        F_PROPERTIES, F_TARGET_ENTITY_ID, F_TARGET_ENTITY_TYPE,
    )
    from ..storage.event import new_event_ids

    n, foff, flen, ev_ms, cr_ms, loff, llen, status = scan
    time_none = np.iinfo(np.int64).min  # TIME_NONE in jsonl_scan.cpp
    ids = new_event_ids(n)
    imported = 0
    # ordered mixed buffer: INSERT OR REPLACE means a duplicate eventId is
    # last-line-wins, so raw rows and python-fallback events must flush in
    # strict file order (consecutive same-kind runs batch together)
    pending: list[tuple[str, object]] = []

    def flush():
        nonlocal imported
        i = 0
        while i < len(pending):
            kind = pending[i][0]
            j = i
            while j < len(pending) and pending[j][0] == kind:
                j += 1
            chunk = [p[1] for p in pending[i:j]]
            if kind == "raw":
                store.insert_raw_rows(chunk, app_id, channel_id)
            else:
                store.insert_batch(chunk, app_id, channel_id, validate=False)
            imported += len(chunk)
            i = j
        pending.clear()

    for k in range(n):
        if status[k]:
            line = data[loff[k]: loff[k] + llen[k]].decode()
            pending.append(("evt", Event.from_json(json.loads(line))))
        else:
            f, ln = foff[k], flen[k]

            def s(slot):
                return (
                    data[f[slot]: f[slot] + ln[slot]].decode()
                    if ln[slot] >= 0 else None
                )

            pending.append(("raw", (
                s(F_EVENT_ID) or ids[k],
                s(F_EVENT),
                s(F_ENTITY_TYPE),
                s(F_ENTITY_ID),
                s(F_TARGET_ENTITY_TYPE),
                s(F_TARGET_ENTITY_ID),
                s(F_PROPERTIES) or "{}",
                int(ev_ms[k]) if ev_ms[k] != time_none else now_ms,
                "[]",
                s(F_PR_ID),
                int(cr_ms[k]) if cr_ms[k] != time_none else now_ms,
            )))
        if len(pending) >= _BATCH:
            flush()
    flush()
    return imported


def export_events(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
    fmt: Optional[str] = None,
) -> int:
    """Event store -> file; returns number exported.

    ``fmt``: ``"json"`` (JSON lines, default), ``"columnar"`` (npz of
    per-field arrays with a zero-copy path into jax), or ``"parquet"``
    (the reference's SparkSQL-Parquet option,
    `export/EventsToFile.scala:30-104`, via pyarrow).  Extensions
    ``.npz``/``.parquet`` imply their formats.
    """
    if fmt is None:
        # extension only: the file does not exist yet
        p = str(path)
        fmt = ("columnar" if p.endswith(".npz")
               else "parquet" if p.endswith(".parquet") else "json")
    if fmt == "parquet":
        return _export_parquet(path, store, app_id, channel_id)
    if fmt == "columnar":
        # np.savez appends '.npz' itself; normalize up front so the
        # reported filename is the one actually written
        return _export_columnar(
            columnar_path(path), store, app_id, channel_id
        )
    if fmt != "json":
        raise ValueError(f"unknown export format {fmt!r}")
    if hasattr(store, "iter_raw_rows"):
        return _export_json_fast(path, store, app_id, channel_id)
    n = 0
    with open(path, "w") as f:
        for e in store.find(app_id=app_id, channel_id=channel_id):
            f.write(json.dumps(e.to_json(), separators=(",", ":")))
            f.write("\n")
            n += 1
    return n


def _export_json_fast(
    path: str | Path, store, app_id: int, channel_id: int
) -> int:
    """Wire-format JSON lines composed from raw storage rows.

    Skips Event construction + property re-serialization: the stored
    ``properties`` text is spliced in as-is (valid JSON; spacing may
    reflect the original import source rather than compact dumps).
    Field order and every other field's formatting match
    ``Event.to_json`` + ``json.dumps(separators=(",", ":"))``; the
    parity test asserts semantic equality line-for-line against the
    portable path.
    """
    from ..storage.event import format_time, from_millis

    n = 0
    d = json.dumps  # escapes string fields exactly like the Event path
    # utf-8 explicitly: spliced properties text may carry raw non-ASCII
    # (the native importer stores source bytes as-is) and must not
    # depend on the locale default encoding
    with open(path, "w", encoding="utf-8") as f:
        for (eid, event, etype, ent_id, tet, tei, props, ev_ms, _tags,
             pr_id, cr_ms) in store.iter_raw_rows(app_id, channel_id):
            parts = [
                f'{{"eventId":{d(eid)}',
                f'"event":{d(event)}',
                f'"entityType":{d(etype)}',
                f'"entityId":{d(ent_id)}',
                f'"properties":{props}',
                f'"eventTime":{d(format_time(from_millis(ev_ms)))}',
            ]
            if tet is not None:
                parts.append(f'"targetEntityType":{d(tet)}')
            if tei is not None:
                parts.append(f'"targetEntityId":{d(tei)}')
            if pr_id is not None:
                parts.append(f'"prId":{d(pr_id)}')
            parts.append(
                f'"creationTime":{d(format_time(from_millis(cr_ms)))}'
            )
            f.write(",".join(parts))
            f.write("}\n")
            n += 1
    return n


def columnar_path(path: str | Path) -> str:
    """The filename a columnar export actually writes."""
    p = str(path)
    return p if p.endswith(".npz") else p + ".npz"


_COLUMNS = (
    "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "eventTime", "eventId", "prId", "creationTime",
)


def _export_columnar(
    path: str | Path, store: EventStore, app_id: int, channel_id: int
) -> int:
    import numpy as np

    cols: dict[str, list[str]] = {c: [] for c in _COLUMNS}
    cols["properties"] = []
    for e in store.find(app_id=app_id, channel_id=channel_id):
        d = e.to_json()
        for c in _COLUMNS:
            cols[c].append(str(d.get(c) or ""))
        props = d.get("properties") or {}
        cols["properties"].append(
            json.dumps(props, separators=(",", ":")) if props else ""
        )
    n = len(cols["event"])
    np.savez_compressed(
        path, **{k: np.asarray(v, dtype=np.str_) for k, v in cols.items()}
    )
    return n


_PARQUET_COLUMNS = (
    "eventId", "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "properties", "eventTime", "tags", "prId",
    "creationTime",
)


def _export_parquet(
    path: str | Path, store: EventStore, app_id: int, channel_id: int
) -> int:
    """Events -> one Parquet file (wire-format fields; `properties` and
    `tags` as JSON text, times as ISO-8601 strings — readable by any
    Parquet consumer, round-trips through :func:`_import_parquet`).
    Streams in `_BATCH`-row record batches: event sets at this repo's
    20M scale must never be resident as Python lists all at once."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    schema = pa.schema([(c, pa.string()) for c in _PARQUET_COLUMNS])
    n = 0
    cols: dict[str, list] = {c: [] for c in _PARQUET_COLUMNS}

    def flush(writer):
        nonlocal cols
        if cols["event"]:
            writer.write_batch(pa.record_batch(
                [pa.array(cols[c], pa.string()) for c in _PARQUET_COLUMNS],
                schema=schema,
            ))
            cols = {c: [] for c in _PARQUET_COLUMNS}

    with pq.ParquetWriter(str(path), schema) as writer:
        for e in store.find(app_id=app_id, channel_id=channel_id):
            d = e.to_json()
            cols["eventId"].append(d.get("eventId"))
            cols["event"].append(d["event"])
            cols["entityType"].append(d["entityType"])
            cols["entityId"].append(d["entityId"])
            cols["targetEntityType"].append(d.get("targetEntityType"))
            cols["targetEntityId"].append(d.get("targetEntityId"))
            cols["properties"].append(
                json.dumps(d.get("properties") or {}, separators=(",", ":"))
            )
            cols["eventTime"].append(d["eventTime"])
            cols["tags"].append(json.dumps(list(e.tags)))
            cols["prId"].append(d.get("prId"))
            cols["creationTime"].append(d["creationTime"])
            n += 1
            if len(cols["event"]) >= _BATCH:
                flush(writer)
        flush(writer)
    return n


def _import_parquet(
    path: str | Path, store: EventStore, app_id: int, channel_id: int
) -> int:
    """Parquet -> event store.  Rows go through ``Event.from_json`` +
    validation — external Parquet files get the same scrutiny as JSON
    lines (the native fast path stays the JSON importer's)."""
    import pyarrow.parquet as pq

    _OPT = ("eventId", "targetEntityType", "targetEntityId", "eventTime",
            "prId", "creationTime")
    imported = 0
    store.init_channel(app_id, channel_id)
    pf = pq.ParquetFile(str(path))
    with store.bulk():
        for rb in pf.iter_batches(batch_size=_BATCH):
            data = {name: rb.column(i).to_pylist()
                    for i, name in enumerate(rb.schema.names)}
            n = rb.num_rows
            none_col = [None] * n
            opt_cols = {name: data.get(name, none_col) for name in _OPT}
            props_col = data.get("properties", none_col)
            tags_col = data.get("tags", none_col)
            batch: list[Event] = []
            for k in range(n):
                d = {
                    "event": data["event"][k],
                    "entityType": data["entityType"][k],
                    "entityId": data["entityId"][k],
                }
                for name in _OPT:
                    v = opt_cols[name][k]
                    if v is not None:
                        d[name] = v
                props = props_col[k]
                if props:
                    d["properties"] = json.loads(props)
                tags = tags_col[k]
                if tags:
                    d["tags"] = (json.loads(tags) if isinstance(tags, str)
                                 else list(tags))
                batch.append(Event.from_json(d))
            if batch:
                store.insert_batch(batch, app_id, channel_id,
                                   validate=False)
                imported += len(batch)
    return imported


def import_events_columnar(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
) -> int:
    """npz columnar file (see :func:`export_events`) -> event store."""
    import numpy as np

    data = np.load(path, allow_pickle=False)
    n = len(data["event"])
    batch: list[Event] = []
    total = 0
    for row in range(n):
        d = {c: str(data[c][row]) for c in _COLUMNS if str(data[c][row])}
        props = str(data["properties"][row])
        if props:
            d["properties"] = json.loads(props)
        batch.append(Event.from_json(d))
        if len(batch) >= _BATCH:
            store.insert_batch(batch, app_id, channel_id)
            total += len(batch)
            batch = []
    if batch:
        store.insert_batch(batch, app_id, channel_id)
        total += len(batch)
    return total


def import_ratings_csv(
    path: str | Path,
    store: EventStore,
    app_id: int,
    channel_id: int = 0,
    event: str = "rate",
    delimiter: str = "::",
    has_header: bool = False,
) -> int:
    """MovieLens-style ratings file (user<delim>item<delim>rating[...]) ->
    rate events — the quickstart data-import path of the recommendation
    template.

    Stores exposing the low-level row sink take a raw-rows fast path —
    at ML-20M scale the Event-object route costs minutes of pure
    overhead.  (A pandas-vectorized parse of this loop was built and
    measured NO faster once the store defers index maintenance during
    bulk scopes — the wall is sqlite executemany + row assembly, which
    both share — so the simple loop stays; see sqlite_events.bulk.)  The schema is framework-shaped, but the entity ids come
    straight from the file and the event name from the caller, so the
    same checks `validate_event` would apply are kept: the event name is
    validated once up front (it is constant) and per-row empty ids raise
    exactly like the Event path did.
    """
    from ..storage.event import (
        EventValidationError, new_event_ids, now_utc, time_millis,
        validate_event,
    )

    # constant across rows: validate once via a representative event
    validate_event(Event(event=event, entity_type="user", entity_id="x",
                         target_entity_type="item", target_entity_id="y",
                         properties=DataMap({"rating": 1.0})))

    raw = hasattr(store, "insert_raw_rows")
    n = 0
    batch: list = []
    now_ms = time_millis(now_utc())
    ids = iter([])
    store.init_channel(app_id, channel_id)

    def flush():
        nonlocal n, batch
        if not batch:
            return
        if raw:
            store.insert_raw_rows(batch, app_id, channel_id)
        else:
            store.insert_batch(batch, app_id, channel_id)
        n += len(batch)
        batch = []

    with open(path) as f, store.bulk():
        if has_header:
            next(f, None)
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split(delimiter)
            u, i, r = parts[0], parts[1], float(parts[2])
            if raw:
                if not u:
                    raise EventValidationError(
                        "entityId must not be empty string."
                    )
                if not i:
                    raise EventValidationError(
                        "targetEntityId must not be empty string."
                    )
                eid = next(ids, None)
                if eid is None:
                    ids = iter(new_event_ids(_BATCH))
                    eid = next(ids)
                batch.append((
                    eid, event, "user", u, "item", i,
                    '{"rating":%s}' % json.dumps(r), now_ms, "[]",
                    None, now_ms,
                ))
            else:
                batch.append(
                    Event(
                        event=event,
                        entity_type="user",
                        entity_id=u,
                        target_entity_type="item",
                        target_entity_id=i,
                        properties=DataMap({"rating": r}),
                    )
                )
            if len(batch) >= _BATCH:
                flush()
        flush()
    return n
