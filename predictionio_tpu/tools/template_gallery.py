"""Template gallery: `pio template list|get` rebuilt offline.

The reference (`tools/console/Template.scala:130-427`) browses a GitHub
gallery, downloads a release zip, rewrites the Scala package name, and
records `template.json` metadata; `verifyTemplateMinVersion` (`:417-427`)
gates `train`/`deploy` on the template's declared minimum framework
version.  This build has no network egress, so the gallery is the set of
built-in template families (SURVEY §2.6) and `template get` scaffolds a
self-contained engine directory — `engine.py` subclassing the built-in
components, `engine.json` variant, `template.json` metadata, README —
that `pio-tpu train`/`deploy` consume directly.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from .. import __version__

__all__ = [
    "GALLERY",
    "TemplateMeta",
    "list_templates",
    "scaffold",
    "verify_template_min_version",
    "TemplateVersionError",
]


@dataclass(frozen=True)
class TemplateMeta:
    name: str
    description: str
    factory: str                     # dotted path to the engine factory
    engine_params: dict = field(default_factory=dict)
    evaluation: Optional[str] = None
    query_example: dict = field(default_factory=dict)


GALLERY: dict[str, TemplateMeta] = {
    "recommendation": TemplateMeta(
        name="recommendation",
        description=(
            "Personalized recommendation via block-ALS on TPU "
            "(scala-parallel-recommendation analogue)"
        ),
        factory="predictionio_tpu.templates.recommendation"
        ".recommendation_engine",
        engine_params={
            "datasource": {
                "params": {"appName": "MyApp", "eventNames": ["rate", "buy"]}
            },
            "algorithms": [
                {
                    "name": "als",
                    "params": {
                        "rank": 10,
                        "numIterations": 20,
                        "lambda": 0.01,
                        "seed": 3,
                    },
                }
            ],
        },
        evaluation="predictionio_tpu.templates.recommendation"
        ".recommendation_evaluation",
        query_example={"user": "1", "num": 4},
    ),
    "similarproduct": TemplateMeta(
        name="similarproduct",
        description=(
            "Similar-product ranking from item factors "
            "(scala-parallel-similarproduct analogue)"
        ),
        factory="predictionio_tpu.templates.similarproduct"
        ".similarproduct_engine",
        engine_params={
            "datasource": {"params": {"appName": "MyApp"}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {"rank": 10, "numIterations": 20,
                               "lambda": 0.01, "seed": 3},
                }
            ],
        },
        query_example={"items": ["1"], "num": 4},
    ),
    "classification": TemplateMeta(
        name="classification",
        description=(
            "Attribute classification: naive bayes / TPU logistic "
            "(scala-parallel-classification analogue)"
        ),
        factory="predictionio_tpu.templates.classification"
        ".classification_engine",
        engine_params={
            "datasource": {"params": {"appName": "MyApp"}},
            "algorithms": [{"name": "naive", "params": {"lambda": 1.0}}],
        },
        query_example={"features": [2.0, 0.0, 0.0]},
    ),
    "ecommercerecommendation": TemplateMeta(
        name="ecommercerecommendation",
        description=(
            "E-commerce recommendation with serving-time event filtering "
            "(scala-parallel-ecommercerecommendation analogue)"
        ),
        factory="predictionio_tpu.templates.ecommerce.ecommerce_engine",
        engine_params={
            "datasource": {"params": {"appName": "MyApp"}},
            "algorithms": [
                {
                    "name": "ecomm",
                    "params": {
                        "appName": "MyApp",
                        "unseenOnly": True,
                        "seenEvents": ["buy", "view"],
                        "rank": 10,
                        "numIterations": 20,
                        "lambda": 0.01,
                        "seed": 3,
                    },
                }
            ],
        },
        query_example={"user": "u1", "num": 4},
    ),
}


def list_templates() -> list[TemplateMeta]:
    return list(GALLERY.values())


_ENGINE_PY = '''\
"""Engine scaffolded from the built-in `{name}` template.

Customize by subclassing the imported components (the reference's
`template get` rewrites a downloaded Scala project; here the framework
components are imported and re-exported so the engine.json stays small).
"""

from {module} import *  # noqa: F401,F403
from {module} import {attr} as engine_factory  # noqa: F401
'''

_README = """\
# {name} (predictionio_tpu template)

{description}

## Usage

    pio-tpu app new MyApp                 # create app + access key
    pio-tpu import --appid <id> --input events.jsonl
    pio-tpu build                         # register the engine
    pio-tpu train                         # train on the TPU mesh
    pio-tpu deploy --port 8000            # serve queries.json

Query example:

    curl -H 'Content-Type: application/json' \\
         -d '{query}' http://localhost:8000/queries.json
"""


def scaffold(template_name: str, target_dir: str | Path) -> Path:
    """`pio template get` analogue: write a runnable engine directory."""
    meta = GALLERY.get(template_name)
    if meta is None:
        raise KeyError(
            f"unknown template {template_name!r}; "
            f"available: {', '.join(sorted(GALLERY))}"
        )
    target = Path(target_dir)
    if target.exists() and any(target.iterdir()):
        raise FileExistsError(f"target directory {target} is not empty")
    target.mkdir(parents=True, exist_ok=True)

    module, _, attr = meta.factory.rpartition(".")
    (target / "engine.py").write_text(
        _ENGINE_PY.format(name=meta.name, module=module, attr=attr)
    )
    # engineFactory points at the scaffolded engine.py (resolved relative
    # to the engine dir by the workflow loader), so user edits there take
    # effect — pointing at the built-in factory would make the file dead.
    variant = {
        "id": meta.name,
        "description": meta.description,
        "engineFactory": "engine.engine_factory",
        **meta.engine_params,
    }
    (target / "engine.json").write_text(json.dumps(variant, indent=2) + "\n")
    # template.json: min-version metadata (Template.scala:417-427 analogue)
    (target / "template.json").write_text(
        json.dumps({"pio": {"version": {"min": __version__}}}, indent=2)
        + "\n"
    )
    (target / "README.md").write_text(
        _README.format(
            name=meta.name,
            description=meta.description,
            query=json.dumps(meta.query_example),
        )
    )
    return target


class TemplateVersionError(RuntimeError):
    pass


def _ver_tuple(v: str) -> tuple[int, ...]:
    parts = []
    for p in v.split("."):
        digits = "".join(c for c in p if c.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def verify_template_min_version(engine_dir: str | Path) -> None:
    """Raise if template.json declares a min version newer than ours."""
    tj = Path(engine_dir) / "template.json"
    if not tj.exists():
        return
    try:
        meta = json.loads(tj.read_text())
        min_v = meta["pio"]["version"]["min"]
    except (ValueError, KeyError, TypeError):
        return
    if _ver_tuple(str(min_v)) > _ver_tuple(__version__):
        raise TemplateVersionError(
            f"template requires predictionio_tpu >= {min_v}, "
            f"this is {__version__}"
        )
