"""Template gallery: `pio template list|get` rebuilt offline.

The reference (`tools/console/Template.scala:130-427`) browses a GitHub
gallery, downloads a release zip, rewrites the Scala package name, and
records `template.json` metadata; `verifyTemplateMinVersion` (`:417-427`)
gates `train`/`deploy` on the template's declared minimum framework
version.  This build has no network egress, so the gallery is the set of
built-in template families (SURVEY §2.6) and `template get` scaffolds a
self-contained engine directory — `engine.py` subclassing the built-in
components, `engine.json` variant, `template.json` metadata, README —
that `pio-tpu train`/`deploy` consume directly.
"""

from __future__ import annotations

import json
import stat
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from .. import __version__

__all__ = [
    "GALLERY",
    "TemplateMeta",
    "fetch_index",
    "list_templates",
    "scaffold",
    "scaffold_from_archive",
    "scaffold_from_index",
    "scaffold_from_url",
    "verify_template_min_version",
    "TemplateVersionError",
]

# remote-fetch guardrails: templates are untrusted input arriving over
# the operator-supplied URL, so the transport is capped before the
# archive hardening in _extract_archive even starts
_MAX_INDEX_BYTES = 4 << 20     # a template INDEX beyond 4 MB is wrong
_MAX_ARCHIVE_BYTES = 256 << 20
_ARCHIVE_SUFFIXES = (".zip", ".tar", ".tar.gz", ".tgz")


@dataclass(frozen=True)
class TemplateMeta:
    name: str
    description: str
    factory: str                     # dotted path to the engine factory
    engine_params: dict = field(default_factory=dict)
    evaluation: Optional[str] = None
    query_example: dict = field(default_factory=dict)


class _Gallery(dict):
    """The template gallery IS a view of the pio-forge engine registry:
    one :class:`~predictionio_tpu.engines.EngineSpec` declaration per
    engine feeds both ``pio-tpu engines list`` and ``template
    list/get`` — the per-template metadata dicts that used to live here
    (and drift from the templates) are gone.

    Built lazily on first access so importing this module doesn't pull
    the template modules (and their jax imports) for commands that
    never touch the gallery; refreshed from the registry on every build
    so engines registered later (``PIO_TPU_ENGINE_PATH``) appear."""

    _built = False

    def _build(self) -> None:
        from ..engines import list_engine_specs

        self.clear()
        for spec in list_engine_specs():
            self[spec.name] = TemplateMeta(
                name=spec.name,
                description=spec.description,
                factory=spec.factory_path,
                engine_params=dict(spec.default_params),
                evaluation=spec.evaluation_path,
                query_example=dict(spec.query_example),
            )
        self._built = True

    def _ensure(self) -> None:
        if not self._built:
            self._build()

    def __getitem__(self, k):
        self._ensure()
        return super().__getitem__(k)

    def get(self, k, default=None):
        self._ensure()
        return super().get(k, default)

    def __iter__(self):
        self._ensure()
        return super().__iter__()

    def __len__(self) -> int:
        self._ensure()
        return super().__len__()

    def __contains__(self, k) -> bool:
        self._ensure()
        return super().__contains__(k)

    def values(self):
        self._ensure()
        return super().values()

    def keys(self):
        self._ensure()
        return super().keys()

    def items(self):
        self._ensure()
        return super().items()


GALLERY: dict[str, TemplateMeta] = _Gallery()


def list_templates() -> list[TemplateMeta]:
    GALLERY._build()  # refresh: late registrations must appear
    return list(GALLERY.values())


_ENGINE_PY = '''\
"""Engine scaffolded from the built-in `{name}` template.

Customize by subclassing the imported components (the reference's
`template get` rewrites a downloaded Scala project; here the framework
components are imported and re-exported so the engine.json stays small).
"""

from {module} import *  # noqa: F401,F403
from {module} import {attr} as engine_factory  # noqa: F401
'''

_README = """\
# {name} (predictionio_tpu template)

{description}

## Usage

    pio-tpu app new MyApp                 # create app + access key
    pio-tpu import --appid <id> --input events.jsonl
    pio-tpu build                         # register the engine
    pio-tpu train                         # train on the TPU mesh
    pio-tpu deploy --port 8000            # serve queries.json

Query example:

    curl -H 'Content-Type: application/json' \\
         -d '{query}' http://localhost:8000/queries.json
"""


def scaffold(template_name: str, target_dir: str | Path) -> Path:
    """`pio template get` analogue: write a runnable engine directory."""
    meta = GALLERY.get(template_name)
    if meta is None:
        raise KeyError(
            f"unknown template {template_name!r}; "
            f"available: {', '.join(sorted(GALLERY))}"
        )
    target = Path(target_dir)
    if target.exists() and any(target.iterdir()):
        raise FileExistsError(f"target directory {target} is not empty")
    target.mkdir(parents=True, exist_ok=True)

    module, _, attr = meta.factory.rpartition(".")
    (target / "engine.py").write_text(
        _ENGINE_PY.format(name=meta.name, module=module, attr=attr)
    )
    # engineFactory points at the scaffolded engine.py (resolved relative
    # to the engine dir by the workflow loader), so user edits there take
    # effect — pointing at the built-in factory would make the file dead.
    variant = {
        "id": meta.name,
        "description": meta.description,
        "engineFactory": "engine.engine_factory",
        **meta.engine_params,
    }
    (target / "engine.json").write_text(json.dumps(variant, indent=2) + "\n")
    # template.json: min-version metadata (Template.scala:417-427 analogue)
    (target / "template.json").write_text(
        json.dumps({"pio": {"version": {"min": __version__}}}, indent=2)
        + "\n"
    )
    (target / "README.md").write_text(
        _README.format(
            name=meta.name,
            description=meta.description,
            query=json.dumps(meta.query_example),
        )
    )
    return target


def _http_get(url: str, max_bytes: int, timeout: float,
              sink=None) -> Optional[bytes]:
    """Streamed GET with a scheme check and a hard size cap (a
    mis-pointed URL must fail fast, not fill the disk).  With ``sink``
    (a writable binary file object) chunks stream straight to it and
    None is returned — archives up to the 256 MB cap never sit in
    memory; without it the body is returned as bytes (small indexes)."""
    import urllib.request
    from urllib.parse import urlparse

    scheme = urlparse(url).scheme
    if scheme not in ("http", "https"):
        raise ValueError(
            f"unsupported URL scheme {scheme!r} for {url!r} "
            "(http/https only)"
        )
    req = urllib.request.Request(
        url, headers={"User-Agent": f"pio-tpu/{__version__}"}
    )
    chunks, size = [], 0
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        while True:
            chunk = resp.read(1 << 20)
            if not chunk:
                break
            size += len(chunk)
            if size > max_bytes:
                raise ValueError(
                    f"download of {url!r} exceeded the {max_bytes} byte "
                    "cap; refusing"
                )
            if sink is not None:
                sink.write(chunk)
            else:
                chunks.append(chunk)
    if sink is not None:
        sink.flush()
        return None
    return b"".join(chunks)


def fetch_index(index_url: str, timeout: float = 20.0) -> list[dict]:
    """Browse a remote template index — the HTTP half of the
    reference's gallery browse (`tools/console/Template.scala:130-170`,
    which lists a GitHub repository; here the index is framework-
    neutral JSON so any static file server can host a gallery).

    Accepts either a bare JSON list or ``{"templates": [...]}``; each
    entry is a dict with at least ``name`` and ``url`` (archive
    location, absolute or relative to the index URL) and optionally
    ``description``.
    """
    raw = _http_get(index_url, _MAX_INDEX_BYTES, timeout)
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ValueError(f"template index at {index_url!r} is not JSON: {e}")
    entries = doc.get("templates") if isinstance(doc, dict) else doc
    if not isinstance(entries, list):
        raise ValueError(
            f"template index at {index_url!r} must be a JSON list or "
            "{'templates': [...]}"
        )
    out = []
    for e in entries:
        if (
            not isinstance(e, dict)
            or not isinstance(e.get("name"), str)
            or not isinstance(e.get("url"), str)
            or not isinstance(e.get("description", ""), str)
        ):
            # untrusted input: a non-string url/name would otherwise
            # surface later as a raw TypeError from urljoin/formatting
            raise ValueError(
                f"template index entry {e!r} needs string 'name' and "
                "'url' (and a string 'description' if present)"
            )
        out.append(e)
    return out


def scaffold_from_url(url: str, target_dir: str | Path,
                      timeout: float = 60.0) -> Path:
    """Download an engine archive over HTTP(S), then run the SAME
    hardened extract-and-validate flow as a local archive — the
    download half of `tools/console/Template.scala:171-300` (fetch
    release archive -> extract -> record metadata).  The transport adds
    nothing to trust: size-capped fetch into a temp file, then every
    local-archive check (member paths, links, engine.json presence,
    min-version gate) applies unchanged."""
    import tempfile
    from urllib.parse import urlparse

    path = urlparse(url).path.lower()
    suffix = next(
        (s for s in _ARCHIVE_SUFFIXES if path.endswith(s)), None
    )
    if suffix is None:
        raise ValueError(
            f"cannot tell the archive type of {url!r} "
            f"(expected a path ending in one of {_ARCHIVE_SUFFIXES})"
        )
    # a doomed scaffold must not pull the archive first
    target = Path(target_dir)
    if target.exists() and any(target.iterdir()):
        raise FileExistsError(f"target directory {target} is not empty")
    with tempfile.NamedTemporaryFile(suffix=suffix) as tmp:
        _http_get(url, _MAX_ARCHIVE_BYTES, timeout, sink=tmp)
        return scaffold_from_archive(tmp.name, target_dir)


def scaffold_from_index(name: str, target_dir: str | Path,
                        index_url: str, timeout: float = 60.0) -> Path:
    """``template get NAME --index-url``: look the name up in the
    remote index, resolve its (possibly relative) archive URL, fetch,
    extract."""
    from urllib.parse import urljoin

    entries = fetch_index(index_url, timeout=timeout)
    by_name = {e["name"]: e for e in entries}
    if name not in by_name:
        raise KeyError(
            f"template {name!r} not in index {index_url!r}; "
            f"available: {', '.join(sorted(by_name)) or '(none)'}"
        )
    return scaffold_from_url(
        urljoin(index_url, by_name[name]["url"]), target_dir,
        timeout=timeout,
    )


def scaffold_from_archive(archive: str | Path, target_dir: str | Path) -> Path:
    """Scaffold an engine directory from a LOCAL zip/tar archive.

    The egress-free half of the reference's template download
    (`tools/console/Template.scala:171-300`: fetch GitHub release
    archive, extract, record metadata) — the fetch itself is out of
    scope in a zero-egress deployment, but a user with an archive in
    hand (shared drive, artifact store, `git archive` of a colleague's
    engine) gets the same extract-and-validate flow:

    * zip / tar / tar.gz / tgz by extension;
    * member paths are validated — absolute paths, ``..`` traversal,
      and symlink/hardlink members are rejected (the archive is
      untrusted input; links could point outside the target);
    * a single GitHub-style top-level directory is stripped;
    * the result must contain ``engine.json`` (otherwise it is not a
      runnable engine dir and the scaffold fails with the member list);
    * ``template.json`` min-version metadata is honored if present
      (checked now, and again by train/deploy) and created pinning the
      current version if absent;
    * extraction happens in a scratch dir renamed into place on
      success — a rejected archive leaves no partial target behind, so
      the user's retry after fixing it doesn't hit "not empty".
    """
    import shutil
    import tempfile

    archive = Path(archive)
    if not archive.exists():
        raise FileNotFoundError(f"archive not found: {archive}")
    target = Path(target_dir)
    if target.exists() and any(target.iterdir()):
        raise FileExistsError(f"target directory {target} is not empty")
    target.parent.mkdir(parents=True, exist_ok=True)
    scratch = Path(tempfile.mkdtemp(
        prefix=f".{target.name}.extract-", dir=target.parent
    ))
    try:
        _extract_archive(archive, scratch)

        # strip a single GitHub-style top-level directory
        entries = list(scratch.iterdir())
        if len(entries) == 1 and entries[0].is_dir():
            inner = entries[0]
            for child in list(inner.iterdir()):
                child.rename(scratch / child.name)
            inner.rmdir()

        if not (scratch / "engine.json").exists():
            found = sorted(
                str(p.relative_to(scratch)) for p in scratch.rglob("*")
            )[:20]
            raise ValueError(
                f"archive {archive.name} does not contain an engine.json "
                f"at its root — not an engine template (contents: {found})"
            )
        tj = scratch / "template.json"
        if not tj.exists():
            tj.write_text(
                json.dumps(
                    {"pio": {"version": {"min": __version__}}}, indent=2
                )
                + "\n"
            )
        verify_template_min_version(scratch)
        if target.exists():  # pre-existing EMPTY dir: replace it
            target.rmdir()
        scratch.rename(target)
    except Exception:
        shutil.rmtree(scratch, ignore_errors=True)
        raise
    return target


def _extract_archive(archive: Path, dest: Path) -> None:
    name = archive.name.lower()
    if name.endswith(".zip"):
        import zipfile

        with zipfile.ZipFile(archive) as zf:
            infos = [m for m in zf.infolist()
                     if not m.filename.endswith("/")]
            # zip stores unix mode bits in the high 16 of external_attr;
            # a symlink entry would otherwise materialize as a regular
            # file holding the link target — reject like the tar path
            for m in infos:
                if stat.S_ISLNK(m.external_attr >> 16):
                    raise ValueError(
                        f"archive {archive.name} contains link member "
                        f"{m.filename!r}; refusing to extract"
                    )
            _check_members([m.filename for m in infos], archive)
            for m in infos:
                out = dest / m.filename
                out.parent.mkdir(parents=True, exist_ok=True)
                out.write_bytes(zf.read(m))
    elif name.endswith((".tar", ".tar.gz", ".tgz")):
        import tarfile

        with tarfile.open(archive) as tf:
            infos = tf.getmembers()
            # links are rejected, not silently dropped: a skipped member
            # would surface much later as a missing file at train time
            for m in infos:
                if m.issym() or m.islnk():
                    raise ValueError(
                        f"archive {archive.name} contains link member "
                        f"{m.name!r}; refusing to extract"
                    )
            files = [m for m in infos if m.isfile()]
            _check_members([m.name for m in files], archive)
            for m in files:
                out = dest / m.name
                out.parent.mkdir(parents=True, exist_ok=True)
                f = tf.extractfile(m)
                assert f is not None
                out.write_bytes(f.read())
    else:
        raise ValueError(
            f"unsupported archive type {archive.name!r} "
            "(expected .zip, .tar, .tar.gz or .tgz)"
        )


def _check_members(names: list[str], archive: Path) -> None:
    """Reject absolute / traversal member paths (untrusted archives).

    Split on BOTH separators, not the host convention: on POSIX,
    ``Path('..\\x')`` is one component, so a Windows-style traversal
    member would pass a pathlib-only check (harmless here, traversal if
    this ever runs on Windows).  Drive-letter prefixes likewise."""
    for m in names:
        parts = m.replace("\\", "/").split("/")
        if (
            m.startswith(("/", "\\"))
            or ".." in parts
            # Windows drive prefix: single letter + ':' at the START
            # only — a POSIX member like '10:30.txt' or 'ab:c' stays
            # extractable; 'c:…' is rejected as a possible drive path
            or (len(m) >= 2 and m[0].isalpha() and m[1] == ":")
        ):
            raise ValueError(
                f"archive {archive.name} contains unsafe member path "
                f"{m!r}; refusing to extract"
            )


class TemplateVersionError(RuntimeError):
    pass


def _ver_tuple(v: str) -> tuple[int, ...]:
    parts = []
    for p in v.split("."):
        digits = "".join(c for c in p if c.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def verify_template_min_version(engine_dir: str | Path) -> None:
    """Raise if template.json declares a min version newer than ours."""
    tj = Path(engine_dir) / "template.json"
    if not tj.exists():
        return
    try:
        meta = json.loads(tj.read_text())
        min_v = meta["pio"]["version"]["min"]
    except (ValueError, KeyError, TypeError):
        return
    if _ver_tuple(str(min_v)) > _ver_tuple(__version__):
        raise TemplateVersionError(
            f"template requires predictionio_tpu >= {min_v}, "
            f"this is {__version__}"
        )
