"""Ops tooling: import/export, CLI (reference `tools` module)."""

from .import_export import export_events, import_events, import_ratings_csv

__all__ = ["export_events", "import_events", "import_ratings_csv"]
