"""piolint driver: file discovery, engines, baseline, output, exit code.

``python -m predictionio_tpu.analysis [paths...]`` with no paths scans
the gate scope — ``predictionio_tpu/``, ``bench*.py``, ``tools/*.py``
relative to the repo root.  Exit code is 1 iff any finding is neither
inline-suppressed nor baselined (``--strict`` ignores the baseline, for
periodic full-debt review).
"""

from __future__ import annotations

import argparse
import json
import subprocess
from pathlib import Path
from typing import Optional

from .asynclint import AsyncEngine
from .core import RULES, Baseline, Finding, SourceFile, load_baseline
from .enginelint import EngineImportEngine
from .jaxlint import JaxEngine
from .locklint import LockEngine
from .timelint import TimeEngine

__all__ = ["analyze_file", "analyze_paths", "repo_root", "main"]

BASELINE_NAME = "piolint.baseline.json"

# deliberately-violating analyzer test inputs: never scanned implicitly
# (tests/test_piolint.py runs the engines on them directly); passing one
# as an explicit single-file argument still works
EXCLUDED_DIR_PARTS = ("piolint_fixtures",)


def _excluded(path: Path) -> bool:
    return any(part in EXCLUDED_DIR_PARTS for part in path.parts)


def repo_root() -> Path:
    """The directory holding the ``predictionio_tpu`` package."""
    return Path(__file__).resolve().parent.parent.parent


def _is_bench_scope(path: Path, root: Path) -> bool:
    """PIO108 (timing-span) scope: benchmark harnesses + tools."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        rel = Path(path.name)
    return rel.name.startswith("bench") or (
        len(rel.parts) > 1 and rel.parts[0] == "tools"
    )


def _is_engine_scope(path: Path, root: Path) -> bool:
    """PIO301 (engine isolation) scope: engine template modules —
    ``predictionio_tpu/templates/*.py`` minus ``_``-prefixed
    infrastructure files (``_common.py`` wraps platform utilities for
    engines; it IS the sanctioned boundary)."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return False
    return (
        len(rel.parts) == 3
        and rel.parts[0] == "predictionio_tpu"
        and rel.parts[1] == "templates"
        and not rel.name.startswith("_")
    )


def _is_pkg_scope(path: Path, root: Path) -> bool:
    """PIO109 (wall-clock duration) scope: the package itself.  Bench
    harnesses/tools keep wall clocks (fenced, coarse — PIO108 covers
    their honesty); production code must not."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return False
    return len(rel.parts) > 1 and rel.parts[0] == "predictionio_tpu"


def default_paths(root: Optional[Path] = None) -> list[Path]:
    root = root or repo_root()
    paths: list[Path] = sorted((root / "predictionio_tpu").rglob("*.py"))
    paths += sorted(root.glob("bench*.py"))
    tools = root / "tools"
    if tools.is_dir():
        paths += sorted(tools.glob("*.py"))
    return paths


def changed_paths(root: Optional[Path] = None) -> list[Path]:
    """Python files currently staged in the git index (pre-commit scope)."""
    root = root or repo_root()
    try:
        out = subprocess.run(
            ["git", "diff", "--cached", "--name-only", "--diff-filter=ACMR"],
            cwd=root, capture_output=True, text=True, check=True,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return []
    paths = []
    for line in out.splitlines():
        p = root / line.strip()
        if p.suffix == ".py" and p.exists() and not _excluded(p):
            paths.append(p)
    return paths


def analyze_file(path: Path, root: Optional[Path] = None) -> list[Finding]:
    """Run both engines over one file."""
    root = root or repo_root()
    try:
        src = SourceFile.load(path, root)
    except (SyntaxError, UnicodeDecodeError, ValueError, OSError) as e:
        # a file the gate scans but can't read or parse IS a finding
        return [Finding(
            rule="PIO100", path=str(path), line=getattr(e, "lineno", 1) or 1,
            col=0, message=f"file does not parse: {e}", scope="",
            snippet="",
        )]
    findings = JaxEngine(
        src, bench_scope=_is_bench_scope(path, root)
    ).run()
    findings += LockEngine(src).run()
    findings += AsyncEngine(src).run()
    if _is_pkg_scope(path, root):
        findings += TimeEngine(src).run()
    if _is_engine_scope(path, root):
        findings += EngineImportEngine(src).run()
    return findings


def analyze_paths(paths: list[Path],
                  root: Optional[Path] = None) -> list[Finding]:
    root = root or repo_root()
    findings: list[Finding] = []
    for p in paths:
        findings += analyze_file(p, root)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _report_json(findings: list[Finding], strict: bool) -> dict:
    active = [f for f in findings if strict or not f.baselined]
    return {
        "version": 1,
        "strict": strict,
        "rules": RULES,
        "counts": {
            "total": len(findings),
            "baselined": sum(f.baselined for f in findings),
            "active": len(active),
        },
        "findings": [f.to_json() for f in findings],
    }


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m predictionio_tpu.analysis",
        description="piolint: JAX-aware static analysis + lock-discipline "
                    "checker (rules PIO1xx/PIO2xx)",
    )
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files or directories to analyze (default: the "
                         "gate scope — predictionio_tpu/, bench*.py, "
                         "tools/*.py)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default: <repo>/{BASELINE_NAME})")
    ap.add_argument("--strict", action="store_true",
                    help="ignore the baseline: every finding fails "
                         "(periodic full-debt review)")
    ap.add_argument("--changed-files", action="store_true",
                    help="analyze only .py files staged in the git index "
                         "(pre-commit mode); overrides positional paths")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline "
                         "file and exit 0")
    ap.add_argument("--report", type=Path, default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            print(f"{code}  {RULES[code]}")
        return 0

    root = repo_root()
    if args.changed_files:
        paths = changed_paths(root)
        if not paths:
            print("piolint: no staged python files; nothing to do")
            return 0
    elif args.paths:
        paths = []
        for p in args.paths:
            if p.is_dir():
                paths += sorted(q for q in p.rglob("*.py")
                                if not _excluded(q))
            else:
                paths.append(p)
    else:
        paths = default_paths(root)

    findings = analyze_paths(paths, root)

    baseline_path = args.baseline or (root / BASELINE_NAME)
    if args.write_baseline:
        Baseline.from_findings(findings).save(baseline_path)
        print(f"piolint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = load_baseline(baseline_path)
    baseline.apply(findings)
    active = [f for f in findings if args.strict or not f.baselined]

    report = _report_json(findings, args.strict)
    if args.report is not None:
        args.report.parent.mkdir(parents=True, exist_ok=True)
        args.report.write_text(json.dumps(report, indent=2) + "\n")

    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            if f.baselined and not args.strict:
                continue
            print(f.text())
        n_base = report["counts"]["baselined"]
        print(f"piolint: {len(paths)} file(s), {len(active)} active "
              f"finding(s), {n_base} baselined"
              + (" (strict: baseline ignored)" if args.strict else ""))
    return 1 if active else 0
