"""piolint driver: file discovery, engines, baseline, output, exit code.

``python -m predictionio_tpu.analysis [paths...]`` with no paths scans
the gate scope — ``predictionio_tpu/``, ``bench*.py``, ``tools/*.py``
relative to the repo root.  Exit code is 1 iff any finding is neither
inline-suppressed nor baselined (``--strict`` ignores the baseline, for
periodic full-debt review, and additionally requires a written
``justification`` on every baselined PIO21x deadlock entry).

Per-file engines (jax/time/async/lock/engine-import) run on each file
independently; the whole-program engines (deadlock PIO21x, contract
PIO4xx) run once over the full analyzed set — ``analyze_paths`` is the
program boundary, so fixtures passed as a single path form a one-file
program and the gate's default scope forms the real one.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import time
from pathlib import Path
from typing import Optional

from .asynclint import AsyncEngine
from .contractlint import ContractEngine
from .core import RULES, Baseline, Finding, SourceFile, load_baseline
from .deadlint import DeadlockEngine
from .enginelint import EngineImportEngine
from .jaxlint import JaxEngine
from .locklint import LockEngine
from .timelint import TimeEngine

__all__ = ["analyze_file", "analyze_paths", "repo_root", "main"]

BASELINE_NAME = "piolint.baseline.json"

# rule prefix -> engine bucket for the per-engine summary counts
ENGINE_BUCKETS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("parse", ("PIO100",)),
    ("jax", ("PIO101", "PIO102", "PIO103", "PIO104", "PIO105",
             "PIO106", "PIO107", "PIO108")),
    ("time", ("PIO109",)),
    ("async", ("PIO110",)),
    ("lock", ("PIO201", "PIO202", "PIO203")),
    ("deadlock", ("PIO210", "PIO211", "PIO212", "PIO213")),
    ("engine", ("PIO301",)),
    ("contract", ("PIO401", "PIO402", "PIO403")),
)

# deliberately-violating analyzer test inputs: never scanned implicitly
# (tests/test_piolint.py runs the engines on them directly); passing one
# as an explicit single-file argument still works
EXCLUDED_DIR_PARTS = ("piolint_fixtures",)


def _excluded(path: Path) -> bool:
    return any(part in EXCLUDED_DIR_PARTS for part in path.parts)


def repo_root() -> Path:
    """The directory holding the ``predictionio_tpu`` package."""
    return Path(__file__).resolve().parent.parent.parent


def _is_bench_scope(path: Path, root: Path) -> bool:
    """PIO108 (timing-span) scope: benchmark harnesses + tools."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        rel = Path(path.name)
    return rel.name.startswith("bench") or (
        len(rel.parts) > 1 and rel.parts[0] == "tools"
    )


def _is_engine_scope(path: Path, root: Path) -> bool:
    """PIO301 (engine isolation) scope: engine template modules —
    ``predictionio_tpu/templates/*.py`` minus ``_``-prefixed
    infrastructure files (``_common.py`` wraps platform utilities for
    engines; it IS the sanctioned boundary)."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return False
    return (
        len(rel.parts) == 3
        and rel.parts[0] == "predictionio_tpu"
        and rel.parts[1] == "templates"
        and not rel.name.startswith("_")
    )


def _is_pkg_scope(path: Path, root: Path) -> bool:
    """PIO109 (wall-clock duration) scope: the package itself.  Bench
    harnesses/tools keep wall clocks (fenced, coarse — PIO108 covers
    their honesty); production code must not."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return False
    return len(rel.parts) > 1 and rel.parts[0] == "predictionio_tpu"


def default_paths(root: Optional[Path] = None) -> list[Path]:
    root = root or repo_root()
    paths: list[Path] = sorted((root / "predictionio_tpu").rglob("*.py"))
    paths += sorted(root.glob("bench*.py"))
    tools = root / "tools"
    if tools.is_dir():
        paths += sorted(tools.glob("*.py"))
    return paths


def changed_paths(root: Optional[Path] = None) -> list[Path]:
    """Python files currently staged in the git index (pre-commit scope).

    Uses ``--name-status -z``: NUL-separated and never C-quoted, so
    renames (``R`` status — take the DESTINATION path, the side that
    exists in the index) and non-ASCII names survive; plain
    ``--name-only`` output C-quotes unusual names into strings that
    fail the existence check and silently drop the file."""
    root = root or repo_root()
    try:
        out = subprocess.run(
            ["git", "diff", "--cached", "--name-status", "-z",
             "--diff-filter=ACMR"],
            cwd=root, capture_output=True, check=True,
        ).stdout.decode("utf-8", "surrogateescape")
    except (OSError, subprocess.CalledProcessError):
        return []
    paths = []
    toks = out.split("\0")
    i = 0
    while i < len(toks):
        status = toks[i].strip()
        if not status:
            i += 1
            continue
        if status[0] in ("R", "C"):
            # "R<score> NUL old NUL new": the destination is staged
            name = toks[i + 2] if i + 2 < len(toks) else ""
            i += 3
        else:
            name = toks[i + 1] if i + 1 < len(toks) else ""
            i += 2
        p = root / name
        if p.suffix == ".py" and p.exists() and not _excluded(p):
            paths.append(p)
    return paths


def _load(path: Path, root: Path):
    """(SourceFile, None) or (None, PIO100 Finding)."""
    try:
        return SourceFile.load(path, root), None
    except (SyntaxError, UnicodeDecodeError, ValueError, OSError) as e:
        # a file the gate scans but can't read or parse IS a finding
        return None, Finding(
            rule="PIO100", path=str(path), line=getattr(e, "lineno", 1) or 1,
            col=0, message=f"file does not parse: {e}", scope="",
            snippet="",
        )


def _file_findings(src: SourceFile, path: Path,
                   root: Path) -> list[Finding]:
    findings = JaxEngine(
        src, bench_scope=_is_bench_scope(path, root)
    ).run()
    findings += LockEngine(src).run()
    findings += AsyncEngine(src).run()
    if _is_pkg_scope(path, root):
        findings += TimeEngine(src).run()
    if _is_engine_scope(path, root):
        findings += EngineImportEngine(src).run()
    return findings


def analyze_file(path: Path, root: Optional[Path] = None) -> list[Finding]:
    """Run the per-file engines over one file (the whole-program
    deadlock/contract engines need the full set — use analyze_paths)."""
    root = root or repo_root()
    src, err = _load(path, root)
    if src is None:
        return [err]
    return _file_findings(src, path, root)


def analyze_paths(paths: list[Path],
                  root: Optional[Path] = None) -> list[Finding]:
    """Per-file engines on each path, then the whole-program engines
    (deadlock PIO21x, contract PIO4xx) over the parsed set."""
    root = root or repo_root()
    findings: list[Finding] = []
    srcs: list[SourceFile] = []
    for p in paths:
        src, err = _load(p, root)
        if src is None:
            findings.append(err)
            continue
        srcs.append(src)
        findings += _file_findings(src, p, root)
    findings += DeadlockEngine(srcs).run()
    findings += ContractEngine(srcs, root).run()
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _report_json(findings: list[Finding], strict: bool) -> dict:
    active = [f for f in findings if strict or not f.baselined]
    return {
        "version": 1,
        "strict": strict,
        "rules": RULES,
        "counts": {
            "total": len(findings),
            "baselined": sum(f.baselined for f in findings),
            "active": len(active),
        },
        "engines": _engine_counts(findings),
        "findings": [f.to_json() for f in findings],
    }


def _engine_counts(findings: list[Finding]) -> dict[str, int]:
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        engine: sum(by_rule.get(r, 0) for r in rules)
        for engine, rules in ENGINE_BUCKETS
    }


def _report_sarif(findings: list[Finding]) -> dict:
    """SARIF 2.1.0: one run, every finding a result; baselined ones
    carry an external suppression so annotators can dim them."""
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "warning" if f.baselined else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": f.line,
                        "startColumn": f.col + 1,
                    },
                },
            }],
        }
        if f.baselined:
            result["suppressions"] = [{"kind": "external"}]
        results.append(result)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "piolint",
                    "rules": [
                        {"id": code,
                         "shortDescription": {"text": RULES[code]}}
                        for code in sorted(RULES)
                    ],
                },
            },
            "results": results,
        }],
    }


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m predictionio_tpu.analysis",
        description="piolint: JAX-aware static analysis, lock-discipline, "
                    "deadlock, and contract-drift checker "
                    "(rules PIO1xx/PIO2xx/PIO3xx/PIO4xx)",
    )
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files or directories to analyze (default: the "
                         "gate scope — predictionio_tpu/, bench*.py, "
                         "tools/*.py)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default: <repo>/{BASELINE_NAME})")
    ap.add_argument("--strict", action="store_true",
                    help="ignore the baseline: every finding fails, and "
                         "every baselined PIO21x deadlock entry must "
                         "carry a written justification "
                         "(periodic full-debt review)")
    ap.add_argument("--changed-files", action="store_true",
                    help="analyze only .py files staged in the git index "
                         "(pre-commit mode); overrides positional paths")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline "
                         "file and exit 0")
    ap.add_argument("--report", type=Path, default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            print(f"{code}  {RULES[code]}")
        return 0

    root = repo_root()
    if args.changed_files:
        paths = changed_paths(root)
        if not paths:
            print("piolint: no staged python files; nothing to do")
            return 0
    elif args.paths:
        paths = []
        for p in args.paths:
            if p.is_dir():
                paths += sorted(q for q in p.rglob("*.py")
                                if not _excluded(q))
            else:
                paths.append(p)
    else:
        paths = default_paths(root)

    t0 = time.perf_counter()
    findings = analyze_paths(paths, root)
    elapsed = time.perf_counter() - t0

    baseline_path = args.baseline or (root / BASELINE_NAME)
    if args.write_baseline:
        Baseline.from_findings(findings).save(baseline_path)
        print(f"piolint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = load_baseline(baseline_path)
    if args.strict:
        # a baselined deadlock hazard without a written reason is just
        # a muted bug: --strict refuses to review around it
        missing = [
            e for e in baseline.entries
            if e.get("rule", "").startswith("PIO21")
            and not str(e.get("justification", "")).strip()
        ]
        if missing:
            for e in missing:
                print(f"piolint: baseline entry {e.get('path')} "
                      f"{e.get('rule')} [{e.get('scope')}] lacks the "
                      "justification required for PIO21x entries")
            return 1
    baseline.apply(findings)
    active = [f for f in findings if args.strict or not f.baselined]

    report = _report_json(findings, args.strict)
    if args.report is not None:
        args.report.parent.mkdir(parents=True, exist_ok=True)
        args.report.write_text(json.dumps(report, indent=2) + "\n")

    if args.format == "json":
        print(json.dumps(report, indent=2))
    elif args.format == "sarif":
        print(json.dumps(_report_sarif(findings), indent=2))
    else:
        for f in findings:
            if f.baselined and not args.strict:
                continue
            print(f.text())
        n_base = report["counts"]["baselined"]
        per_engine = " | ".join(
            f"{name} {count}"
            for name, count in report["engines"].items())
        print(f"piolint: {len(paths)} file(s), {len(active)} active "
              f"finding(s), {n_base} baselined "
              f"[{per_engine}] in {elapsed:.1f}s"
              + (" (strict: baseline ignored)" if args.strict else ""))
    return 1 if active else 0
