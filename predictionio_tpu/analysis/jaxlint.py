"""piolint JAX engine (PIO1xx): traced-code hazards, found statically.

Walks every function reachable from a ``jax.jit``/``pjit``/``shard_map``
application (decorator form, ``g = jax.jit(f)`` call form, the
``functools.partial(jax.jit, ...)(f)`` idiom, and functions handed to
tracing higher-order ops like ``lax.scan``) and runs a forward taint
analysis: non-static parameters are tracers, values derived from
tracers are tracers, and ``.shape``/``.dtype``-style attribute reads
strip the taint (shapes are static under tracing).  Host syncs,
data-dependent Python control flow, string formatting of tracers,
unhashable static args, and donated-buffer reuse all fall out as taint
queries at specific syntax nodes.

Everything is module-local and first-order: a callback passed into
another function is not followed.  That bounds false negatives, and the
baseline mechanism absorbs the (rare) false positive — this is a gate,
not a verifier.

PIO108 (unfenced timing spans) lives here too because it needs the same
"which calls dispatch device work" knowledge; it only runs on files the
driver marks as benchmark scope (``bench*.py``, ``tools/``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional, Union

from .core import Finding, SourceFile

__all__ = ["JaxEngine"]

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

# attribute reads that yield static (trace-time) metadata, not a tracer
SHAPE_ATTRS = {
    "shape", "dtype", "ndim", "size", "sharding", "device", "devices",
    "weak_type", "aval", "itemsize", "nbytes",
}

# higher-order jax ops whose function arguments run under tracing
TRACING_HOFS = {
    "scan", "cond", "while_loop", "fori_loop", "switch", "associative_scan",
    "vmap", "grad", "value_and_grad", "jacfwd", "jacrev", "pmap",
    "remat", "checkpoint", "custom_jvp", "custom_vjp", "map",
}

JIT_ATTRS = {"jit", "pjit", "shard_map"}

UNHASHABLE_LITERALS = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp,
    ast.GeneratorExp,
)

TIME_FUNCS = {"time", "perf_counter", "monotonic", "process_time"}

# call names that force device completion (or copy to host) — a timed
# span containing one of these before the closing timer read is honest
FENCE_ATTRS = {"block_until_ready", "device_get", "item", "fence",
               "effects_barrier"}
FENCE_NAMES = {"fence", "float", "int"}


def _dotted(node: ast.AST) -> Optional[list[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None when the chain has calls etc."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _str_elems(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def _int_elems(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


@dataclass
class FuncInfo:
    node: FuncNode
    qualname: str
    params: list[str]
    cls: Optional[str] = None        # owning class name, if a method
    parent: Optional[FuncNode] = None  # enclosing function, if nested
    locals_map: dict[str, "FuncInfo"] = field(default_factory=dict)


@dataclass
class JitInfo:
    """One jit application: the wrapped local function + arg semantics."""
    func: Optional[FuncInfo]
    static: set[str] = field(default_factory=set)
    donate: set[str] = field(default_factory=set)


class JaxEngine:
    def __init__(self, src: SourceFile, bench_scope: bool = False):
        self.src = src
        self.bench_scope = bench_scope
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()
        self.imports = _ImportScan()
        self.imports.visit(src.tree)
        self.functions: dict[int, FuncInfo] = {}
        self.module_funcs: dict[str, FuncInfo] = {}
        self.class_methods: dict[str, dict[str, FuncInfo]] = {}
        self._collect_functions()
        self._parents: dict[int, ast.AST] = {}
        for parent in src.walk():
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        self.jit_apps: list[JitInfo] = []
        self.wrappers: dict[str, JitInfo] = {}  # bound name -> jit info
        self._collect_jit_applications()

    # -- public ------------------------------------------------------------
    def run(self) -> list[Finding]:
        self._run_taint()
        self._check_static_args()
        self._check_donation()
        if self.bench_scope:
            self._check_timing_spans()
        return self.findings

    def _emit(self, rule: str, node: ast.AST, message: str,
              scope: str = "") -> None:
        key = (rule, getattr(node, "lineno", 0),
               getattr(node, "col_offset", 0))
        if key in self._seen:
            return
        self._seen.add(key)
        f = self.src.finding(rule, node, message, scope)
        if f is not None:
            self.findings.append(f)

    # -- structure collection ---------------------------------------------
    def _collect_functions(self) -> None:
        def stmts_of(body):
            """Statements in ``body``, descending through control flow
            (if/try/with/for/while) but NOT into defs/classes."""
            for stmt in body:
                yield stmt
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                for attr in ("body", "orelse", "finalbody"):
                    yield from stmts_of(getattr(stmt, attr, []))
                for h in getattr(stmt, "handlers", []):
                    yield from stmts_of(h.body)

        def walk(body, qualprefix, cls, parent):
            infos = {}
            for stmt in stmts_of(body):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    a = stmt.args
                    params = [p.arg for p in
                              a.posonlyargs + a.args + a.kwonlyargs]
                    info = FuncInfo(
                        node=stmt,
                        qualname=(qualprefix + stmt.name),
                        params=params, cls=cls, parent=parent,
                    )
                    self.functions[id(stmt)] = info
                    infos[stmt.name] = info
                    info.locals_map = walk(stmt.body, info.qualname + ".",
                                           cls, stmt)
                elif isinstance(stmt, ast.ClassDef):
                    self.class_methods[stmt.name] = walk(
                        stmt.body, qualprefix + stmt.name + ".",
                        stmt.name, None,
                    )
            return infos

        self.module_funcs = walk(self.src.tree.body, "", None, None)

    def _resolve_call(self, call: ast.Call,
                      ctx: Optional[FuncInfo]) -> Optional[FuncInfo]:
        """Resolve a call target to a module-local FuncInfo."""
        fn = call.func
        if isinstance(fn, ast.Name):
            cur = ctx
            while cur is not None:
                if fn.id in cur.locals_map:
                    return cur.locals_map[fn.id]
                cur = (self.functions.get(id(cur.parent))
                       if cur.parent is not None else None)
            return self.module_funcs.get(fn.id)
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("self", "cls") and ctx is not None
                and ctx.cls is not None):
            return self.class_methods.get(ctx.cls, {}).get(fn.attr)
        return None

    # -- jit application discovery ----------------------------------------
    def _is_jit_expr(self, node: ast.AST) -> bool:
        """Is this expression jax.jit / jit / pjit / shard_map itself?"""
        if isinstance(node, ast.Name):
            return node.id in self.imports.jit_names
        parts = _dotted(node)
        if parts is None:
            return False
        root, last = parts[0], parts[-1]
        return (root in self.imports.jax_aliases and last in JIT_ATTRS)

    def _jit_call_semantics(self, call: ast.Call,
                            func: Optional[FuncInfo]) -> JitInfo:
        info = JitInfo(func=func)
        params = func.params if func is not None else []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                info.static |= set(_str_elems(kw.value))
            elif kw.arg == "static_argnums":
                for i in _int_elems(kw.value):
                    if 0 <= i < len(params):
                        info.static.add(params[i])
            elif kw.arg == "donate_argnames":
                info.donate |= set(_str_elems(kw.value))
            elif kw.arg == "donate_argnums":
                for i in _int_elems(kw.value):
                    if 0 <= i < len(params):
                        info.donate.add(params[i])
        return info

    def _collect_jit_applications(self) -> None:
        # decorator form, incl. partial(jax.jit, ...) stacks
        for info in self.functions.values():
            for dec in info.node.decorator_list:
                jit = None
                if self._is_jit_expr(dec):
                    jit = JitInfo(func=info)
                elif isinstance(dec, ast.Call):
                    fn = dec.func
                    parts = _dotted(fn)
                    is_partial = (
                        parts is not None
                        and (parts[-1] == "partial"
                             or parts[0] in self.imports.partial_names)
                    )
                    if is_partial and dec.args \
                            and self._is_jit_expr(dec.args[0]):
                        jit = self._jit_call_semantics(dec, info)
                    elif self._is_jit_expr(fn):
                        # @jax.jit(static_argnames=...) config-call form
                        jit = self._jit_call_semantics(dec, info)
                if jit is not None:
                    self.jit_apps.append(jit)
                    self.wrappers.setdefault(info.node.name, jit)
        # call form: jax.jit(f, ...) / functools.partial(jax.jit, ...)(f)
        for node in self.src.walk():
            if not isinstance(node, ast.Call):
                continue
            jit_call = None
            if self._is_jit_expr(node.func):
                jit_call = node
            elif (isinstance(node.func, ast.Call)
                  and node.func.args
                  and self._is_jit_expr(node.func.args[0])):
                parts = _dotted(node.func.func)
                if parts is not None and (
                        parts[-1] == "partial"
                        or parts[0] in self.imports.partial_names):
                    jit_call = node  # partial(jax.jit, kw)(f): kws on inner
            if jit_call is None or not jit_call.args:
                continue
            target = jit_call.args[0]
            func = None
            if isinstance(target, ast.Name):
                func = self.module_funcs.get(target.id)
                if func is None:
                    for fi in self.functions.values():
                        if fi.node.name == target.id:
                            func = fi
                            break
            if func is None:
                continue
            kw_src = (node.func if isinstance(node.func, ast.Call)
                      and not self._is_jit_expr(node.func) else jit_call)
            jit = self._jit_call_semantics(kw_src, func)
            self.jit_apps.append(jit)
            parent = self._parent_of(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        self.wrappers[t.id] = jit
        # tracing HOFs: lax.scan(step, ...), jax.vmap(f), ...
        for node in self.src.walk():
            if not isinstance(node, ast.Call):
                continue
            parts = _dotted(node.func)
            if parts is None or parts[-1] not in TRACING_HOFS:
                continue
            root = parts[0]
            if root not in self.imports.jax_aliases \
                    and root not in ("lax",) \
                    and parts[-1] not in ("vmap", "grad", "value_and_grad",
                                          "pmap"):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    fi = self._resolve_name_func(arg.id)
                    if fi is not None:
                        self.jit_apps.append(JitInfo(func=fi))

    def _resolve_name_func(self, name: str) -> Optional[FuncInfo]:
        if name in self.module_funcs:
            return self.module_funcs[name]
        for fi in self.functions.values():
            if fi.node.name == name:
                return fi
        return None

    def _parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    # -- taint analysis ----------------------------------------------------
    def _run_taint(self) -> None:
        worklist: list[tuple[FuncInfo, frozenset]] = []
        for jit in self.jit_apps:
            if jit.func is None:
                continue
            tainted = frozenset(
                p for p in jit.func.params
                if p not in jit.static and p not in ("self", "cls")
            )
            worklist.append((jit.func, tainted))
        visited: set[tuple[int, frozenset]] = set()
        while worklist:
            func, tainted = worklist.pop()
            key = (id(func.node), tainted)
            if key in visited or len(visited) > 4000:
                continue
            visited.add(key)
            walker = _TaintWalker(self, func, set(tainted))
            walker.run()
            for callee, callee_taint in walker.calls_out:
                worklist.append((callee, callee_taint))


    # -- PIO105: unhashable static args -----------------------------------
    def _check_static_args(self) -> None:
        for jit in self.jit_apps:
            if jit.func is None or not jit.static:
                continue
            # static param with an unhashable default
            a = jit.func.node.args
            pos = a.posonlyargs + a.args
            defaults = a.defaults
            for p, d in zip(pos[len(pos) - len(defaults):], defaults):
                if p.arg in jit.static and isinstance(d, UNHASHABLE_LITERALS):
                    self._emit(
                        "PIO105", d,
                        f"static argument {p.arg!r} of "
                        f"{jit.func.qualname}() has an unhashable default "
                        "(jit static args are dict keys: every distinct "
                        "value is a fresh compile, unhashable ones crash)",
                        jit.func.qualname,
                    )
            for kd, d in zip(a.kwonlyargs, a.kw_defaults):
                if d is not None and kd.arg in jit.static \
                        and isinstance(d, UNHASHABLE_LITERALS):
                    self._emit(
                        "PIO105", d,
                        f"static argument {kd.arg!r} of "
                        f"{jit.func.qualname}() has an unhashable default",
                        jit.func.qualname,
                    )
        # call sites of jitted wrappers binding literals to static params
        for node in self.src.walk():
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            jit = self.wrappers.get(node.func.id)
            if jit is None or jit.func is None or not jit.static:
                continue
            params = jit.func.params
            for i, arg in enumerate(node.args):
                if i < len(params) and params[i] in jit.static \
                        and isinstance(arg, UNHASHABLE_LITERALS):
                    self._emit(
                        "PIO105", arg,
                        f"unhashable literal bound to static argument "
                        f"{params[i]!r} of {node.func.id}() — every call "
                        "recompiles (or TypeErrors)",
                    )
            for kw in node.keywords:
                if kw.arg in jit.static \
                        and isinstance(kw.value, UNHASHABLE_LITERALS):
                    self._emit(
                        "PIO105", kw.value,
                        f"unhashable literal bound to static argument "
                        f"{kw.arg!r} of {node.func.id}()",
                    )

    # -- PIO107: donated-buffer reuse -------------------------------------
    def _check_donation(self) -> None:
        for node in self.src.walk():
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            jit = self.wrappers.get(node.func.id)
            if jit is None or jit.func is None or not jit.donate:
                continue
            params = jit.func.params
            donated_names: list[str] = []
            for i, arg in enumerate(node.args):
                if i < len(params) and params[i] in jit.donate \
                        and isinstance(arg, ast.Name):
                    donated_names.append(arg.id)
            for kw in node.keywords:
                if kw.arg in jit.donate and isinstance(kw.value, ast.Name):
                    donated_names.append(kw.value.id)
            if not donated_names:
                continue
            assign = self._parent_of(node)
            rebound: set[str] = set()
            if isinstance(assign, ast.Assign):
                for t in assign.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            rebound.add(n.id)
            scope = self._enclosing_scope(node)
            # a multi-line call's own argument lines are not "after" it
            call_line = getattr(node, "end_lineno", None) or node.lineno
            for name in donated_names:
                if name in rebound:
                    continue
                use = self._use_after(scope, name, call_line)
                if use is not None:
                    self._emit(
                        "PIO107", use,
                        f"{name!r} was donated to {node.func.id}() on line "
                        f"{call_line} (donate_argnums); its buffer may be "
                        "reused by XLA — reading it afterwards is invalid",
                    )

    def _enclosing_scope(self, node: ast.AST) -> ast.AST:
        cur = self._parent_of(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            cur = self._parent_of(cur)
        return cur if cur is not None else self.src.tree

    @staticmethod
    def _use_after(scope: ast.AST, name: str,
                   call_line: int) -> Optional[ast.AST]:
        next_bind = None
        for n in ast.walk(scope):
            if isinstance(n, ast.Name) and n.id == name \
                    and isinstance(n.ctx, (ast.Store, ast.Del)) \
                    and n.lineno > call_line:
                if next_bind is None or n.lineno < next_bind:
                    next_bind = n.lineno
        for n in ast.walk(scope):
            if isinstance(n, ast.Name) and n.id == name \
                    and isinstance(n.ctx, ast.Load) \
                    and n.lineno > call_line \
                    and (next_bind is None or n.lineno < next_bind):
                return n
        return None

    # -- PIO108: unfenced timing spans (bench scope) -----------------------
    def _is_time_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        parts = _dotted(node.func)
        if parts is None:
            return False
        if len(parts) == 2 and parts[0] in self.imports.time_aliases \
                and parts[1] in TIME_FUNCS:
            return True
        return len(parts) == 1 and parts[0] in self.imports.time_names

    def _is_fence_call(self, node: ast.Call) -> bool:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in FENCE_ATTRS:
                return True
            parts = _dotted(fn)
            if parts is not None and parts[0] in self.imports.np_aliases \
                    and parts[-1] in ("asarray", "array"):
                return True
            return False
        if isinstance(fn, ast.Name):
            return fn.id in FENCE_NAMES
        return False

    # jax.* calls that are metadata/bookkeeping, not device compute
    _JAX_NONCOMPUTE = {
        "devices", "device_count", "local_device_count", "local_devices",
        "process_index", "process_count", "default_backend",
        "clear_caches", "profiler", "config", "trace", "named_scope",
    }

    def _is_device_call(self, node: ast.Call) -> bool:
        parts = _dotted(node.func)
        if parts is not None:
            root = parts[0]
            if parts[-1] in FENCE_ATTRS:
                return False
            if root in self.imports.jnp_aliases:
                return True
            if root in self.imports.jax_aliases and len(parts) > 1 \
                    and not (set(parts[1:]) & self._JAX_NONCOMPUTE):
                return True
        if isinstance(node.func, ast.Name) \
                and node.func.id in self.wrappers:
            return True
        return False

    def _check_timing_spans(self) -> None:
        scopes: list[ast.AST] = [self.src.tree] + [
            fi.node for fi in self.functions.values()
        ]
        for scope in scopes:
            starts: list[tuple[str, int]] = []
            uses: list[tuple[str, int, ast.AST]] = []
            body_nodes = list(ast.walk(scope))
            own = [n for n in body_nodes
                   if self._enclosing_scope(n) is scope
                   or isinstance(scope, ast.Module)]
            for n in own:
                if isinstance(n, ast.Assign) and self._is_time_call(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            starts.append((t.id, n.lineno))
                if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub) \
                        and self._is_time_call(n.left) \
                        and isinstance(n.right, ast.Name):
                    uses.append((n.right.id, n.lineno, n))
            for name, use_line, use_node in uses:
                cands = [ln for (nm, ln) in starts
                         if nm == name and ln < use_line]
                if not cands:
                    continue
                t0_line = max(cands)
                device, fence = False, False
                for n in own:
                    if not isinstance(n, ast.Call):
                        continue
                    if not (t0_line < n.lineno <= use_line):
                        continue
                    if self._is_fence_call(n):
                        fence = True
                    elif self._is_device_call(n):
                        device = True
                if device and not fence:
                    self._emit(
                        "PIO108", use_node,
                        f"timing span ({name!r} from line {t0_line}) "
                        "covers device dispatch but no fence/"
                        "block_until_ready — it measures dispatch, "
                        "not execution",
                    )


class _ImportScan(ast.NodeVisitor):
    """Module import aliases the engine needs to resolve names."""

    def __init__(self):
        self.jax_aliases: set[str] = set()
        self.jnp_aliases: set[str] = set()
        self.np_aliases: set[str] = set()
        self.time_aliases: set[str] = set()
        self.time_names: set[str] = set()    # from time import perf_counter
        self.partial_names: set[str] = set()
        self.jit_names: set[str] = set()     # from jax import jit/pjit

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            bound = a.asname or a.name.split(".")[0]
            if a.name == "jax":
                self.jax_aliases.add(bound)
            elif a.name in ("jax.numpy",):
                self.jnp_aliases.add(a.asname or "jax.numpy")
            elif a.name == "numpy":
                self.np_aliases.add(bound)
            elif a.name == "time":
                self.time_aliases.add(bound)
            elif a.name == "functools":
                pass

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        for a in node.names:
            bound = a.asname or a.name
            if mod == "jax" and a.name == "numpy":
                self.jnp_aliases.add(bound)
            elif mod == "jax" and a.name in JIT_ATTRS:
                self.jit_names.add(bound)
            elif mod.startswith("jax") and a.name in JIT_ATTRS:
                self.jit_names.add(bound)
            elif mod == "functools" and a.name == "partial":
                self.partial_names.add(bound)
            elif mod == "time" and a.name in TIME_FUNCS:
                self.time_names.add(bound)


class _TaintWalker:
    """Forward taint pass over one function body under one taint seed."""

    def __init__(self, engine: JaxEngine, func: FuncInfo, tainted: set[str]):
        self.e = engine
        self.func = func
        self.tainted = tainted
        self.calls_out: list[tuple[FuncInfo, frozenset]] = []

    def run(self) -> None:
        # two passes so loop-carried taint reaches first-pass reads
        self._walk_body(self.func.node.body)
        self._walk_body(self.func.node.body)

    # -- statements --------------------------------------------------------
    def _walk_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._walk_stmt(stmt)

    def _walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # analyzed when called / scheduled separately
        if isinstance(stmt, ast.Assign):
            t = self.taint(stmt.value)
            for target in stmt.targets:
                self._bind(target, t)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self.taint(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            t = self.taint(stmt.value)
            if isinstance(stmt.target, ast.Name):
                if t:
                    self.tainted.add(stmt.target.id)
                elif stmt.target.id in self.tainted:
                    pass  # stays tainted
        elif isinstance(stmt, (ast.If, ast.While)):
            if self._branch_taint(stmt.test):
                self.e._emit(
                    "PIO104", stmt.test,
                    "Python control flow on a traced value: under jit "
                    "this either crashes (ConcretizationTypeError) or "
                    "recompiles per value — use lax.cond/jnp.where",
                    self.func.qualname,
                )
            else:
                self.taint(stmt.test)
            self._walk_body(stmt.body)
            self._walk_body(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            if self._branch_taint(stmt.test):
                self.e._emit(
                    "PIO104", stmt.test,
                    "assert on a traced value inside jit-traced code",
                    self.func.qualname,
                )
        elif isinstance(stmt, ast.For):
            it = self.taint(stmt.iter)
            self._bind(stmt.target, it)
            self._walk_body(stmt.body)
            self._walk_body(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                t = self.taint(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, t)
            self._walk_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._walk_body(stmt.body)
            for h in stmt.handlers:
                self._walk_body(h.body)
            self._walk_body(stmt.orelse)
            self._walk_body(stmt.finalbody)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.taint(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self.taint(stmt.value)
        elif isinstance(stmt, (ast.Raise,)):
            if stmt.exc is not None:
                self.taint(stmt.exc)
        elif isinstance(stmt, ast.Delete):
            pass
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.taint(child)
                elif isinstance(child, ast.stmt):
                    self._walk_stmt(child)

    def _bind(self, target: ast.expr, tainted: bool) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                if tainted:
                    self.tainted.add(n.id)
                else:
                    self.tainted.discard(n.id)

    def _branch_taint(self, test: ast.expr) -> bool:
        """Taint of a branch condition, with identity/None checks
        excluded: ``x is None`` / ``isinstance(x, T)`` inspect the python
        value at trace time and are standard, safe jit idioms."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return False
        if isinstance(test, ast.Call) \
                and isinstance(test.func, ast.Name) \
                and test.func.id in ("isinstance", "hasattr", "callable"):
            return False
        if isinstance(test, ast.BoolOp):
            return any(self._branch_taint(v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._branch_taint(test.operand)
        return self.taint(test)

    # -- expressions -------------------------------------------------------
    def taint(self, node: Optional[ast.expr]) -> bool:
        """Evaluate taint of an expression, emitting findings for
        host-sync / formatting uses of tainted values on the way."""
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            base = self.taint(node.value)
            if node.attr in SHAPE_ATTRS:
                return False
            return base
        if isinstance(node, ast.Subscript):
            return self.taint(node.value) or self.taint(node.slice)
        if isinstance(node, ast.Call):
            return self._taint_call(node)
        if isinstance(node, ast.BinOp):
            return self.taint(node.left) | self.taint(node.right)
        if isinstance(node, ast.BoolOp):
            return any([self.taint(v) for v in node.values])
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.Compare):
            tainted = self.taint(node.left)
            for c in node.comparators:
                tainted |= self.taint(c)
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in node.ops):
                return False
            return tainted
        if isinstance(node, ast.IfExp):
            t = self._branch_taint(node.test)
            if t:
                self.e._emit(
                    "PIO104", node.test,
                    "conditional expression on a traced value inside "
                    "jit-traced code — use jnp.where/lax.select",
                    self.func.qualname,
                )
            return self.taint(node.body) | self.taint(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self.taint(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            return any([self.taint(v) for v in node.values]
                       + [self.taint(k) for k in node.keys if k is not None])
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue) \
                        and self.taint(v.value):
                    self.e._emit(
                        "PIO106", v.value,
                        "f-string interpolation of a traced value: forces "
                        "a host sync at trace time and bakes the traced "
                        "value's repr into the compiled artifact",
                        self.func.qualname,
                    )
            return False
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            tainted = False
            for gen in node.generators:
                t = self.taint(gen.iter)
                self._bind(gen.target, t)
                tainted |= t
            if isinstance(node, ast.DictComp):
                tainted |= self.taint(node.key) | self.taint(node.value)
            else:
                tainted |= self.taint(node.elt)
            return tainted
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, (ast.NamedExpr,)):
            t = self.taint(node.value)
            self._bind(node.target, t)
            return t
        # fallback: any tainted child expression
        return any([self.taint(c) for c in ast.iter_child_nodes(node)
                    if isinstance(c, ast.expr)])

    def _taint_call(self, node: ast.Call) -> bool:
        fn = node.func
        arg_taints = [self.taint(a) for a in node.args]
        kw_taints = {kw.arg: self.taint(kw.value) for kw in node.keywords}
        any_tainted = any(arg_taints) or any(kw_taints.values())

        # host-sync checks -------------------------------------------------
        if isinstance(fn, ast.Attribute) and fn.attr in ("item", "tolist"):
            if self.taint(fn.value):
                self.e._emit(
                    "PIO101", node,
                    f".{fn.attr}() on a traced value inside jit-traced "
                    "code: blocks on device transfer (or "
                    "ConcretizationTypeError under trace)",
                    self.func.qualname,
                )
            return False
        if isinstance(fn, ast.Name) and fn.id in ("float", "int", "bool",
                                                  "complex"):
            if any_tainted:
                self.e._emit(
                    "PIO102", node,
                    f"{fn.id}() forces a traced value to a Python scalar "
                    "inside jit-traced code (ConcretizationTypeError / "
                    "host sync)",
                    self.func.qualname,
                )
            return False
        parts = _dotted(fn)
        if parts is not None and parts[0] in self.e.imports.np_aliases \
                and parts[-1] in ("asarray", "array", "copy", "ascontiguousarray"):
            if any_tainted:
                self.e._emit(
                    "PIO103", node,
                    f"numpy {'.'.join(parts)}() on a traced value inside "
                    "jit-traced code: device->host copy per call (use "
                    "jnp equivalents, or materialize outside jit)",
                    self.func.qualname,
                )
            return False
        if isinstance(fn, ast.Name) and fn.id in ("str", "repr", "format"):
            if any_tainted:
                self.e._emit(
                    "PIO106", node,
                    f"{fn.id}() of a traced value inside jit-traced code "
                    "leaks the trace-time repr into compiled constants",
                    self.func.qualname,
                )
            return False
        if isinstance(fn, ast.Attribute) and fn.attr == "format" \
                and any_tainted:
            self.e._emit(
                "PIO106", node,
                "str.format() of a traced value inside jit-traced code",
                self.func.qualname,
            )
            return False

        # untainting / neutral builtins -----------------------------------
        if isinstance(fn, ast.Name) and fn.id in ("len", "isinstance",
                                                  "hasattr", "getattr",
                                                  "type", "print", "range"):
            return False

        # propagate into module-local callees -----------------------------
        callee = self.e._resolve_call(node, self.func)
        if callee is not None and callee.node is not self.func.node:
            taints: set[str] = set()
            params = callee.params
            offset = 1 if params[:1] in (["self"], ["cls"]) \
                and isinstance(fn, ast.Attribute) else 0
            for i, t in enumerate(arg_taints):
                if t and i + offset < len(params):
                    taints.add(params[i + offset])
            for name, t in kw_taints.items():
                if t and name in params:
                    taints.add(name)
            # closure reads: a nested function sees our tainted locals
            # (seeded as extra tainted names; harmless if unused there)
            if callee.parent is self.func.node:
                taints |= self.tainted
            self.calls_out.append((callee, frozenset(taints)))
        # method call on a tainted receiver stays tainted (.astype etc.)
        recv_tainted = (isinstance(fn, ast.Attribute)
                        and self.taint(fn.value))
        return any_tainted or recv_tainted
