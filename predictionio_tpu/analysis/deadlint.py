"""piolint deadlock engine (PIO210–213): whole-program lock analysis.

`locklint.py` answers "is this attribute touched without its lock"
class by class; this engine answers the questions that need the whole
program at once — the bug class PR 16 (a failed WAL group flush
wedging every later ``barrier()``) and PR 17 (callbacks fired at end
of dispatch turn) shipped by accident:

* **PIO210 lock-order inversion.**  Every ``with self._X`` /
  ``self._X.acquire()`` on a `threading.Lock`/`RLock`/`Condition`
  attribute is an acquisition of the class-qualified lock
  ``Class._X``.  Acquisitions reachable while another lock is held —
  directly, or through a bounded-depth interprocedural walk over
  ``self.method()`` calls and ``self._attr.method()`` calls whose
  receiver type is known from a constructor assignment
  (``self._wal = GroupCommitWAL(...)``) — become edges in a lock-order
  graph.  A cycle is a deadlock waiting for the right interleaving;
  the finding prints BOTH witness paths (file:line frames) so the fix
  is mechanical: pick one order.
* **PIO211 callback under lock.**  A user-supplied callable — a
  parameter or attribute named like a callback (``on_done``,
  ``weight_fn``, ``batch_fn``, ``*_hook``, ``*_cb``, fault hooks,
  health probes) or a local assigned from one — is invoked while a
  lock is statically held.  The callee can take any lock or block
  forever; the exact shape of the PR 11/17 bugs.
* **PIO212 blocking under lock.**  asynclint's blocking-call taxonomy
  (``time.sleep``, blocking socket I/O, untimed ``Queue.get/put``)
  plus ``os.fsync``, ``open()``, ``subprocess.*`` and untimed
  ``Event.wait()``, scoped to lock-held regions instead of coroutines.
  ``Condition.wait`` on the *held* condition is exempt — it releases
  the lock; that is PIO213's territory.
* **PIO213 condition-variable discipline.**  An untimed ``cv.wait()``
  not wrapped in a loop (a single wait is a missed-wakeup/spurious-
  wakeup bug), a ``wait``/``wait_for`` without holding the condition's
  lock, and ``notify``/``notify_all`` off-lock.  ``Condition(lock)``
  aliasing is tracked: holding ``self._lock`` counts as holding a
  ``self._cv`` built from it, and vice versa.

Precision notes shared with locklint: ``__init__``/``__del__`` are
exempt (construction happens-before sharing); explicit
``self._X.release()`` / ``.acquire()`` statements update the running
held set (the release-around-device-call idiom in
``MicroBatcher._lead`` analyzes as UNLOCKED across the device call);
nested ``def``/``lambda`` bodies are pruned (other execution context);
helper methods are analyzed with the *intersection* of the lock sets
their intra-class call sites hold, computed to fixpoint (so
``_claim_locked``-style helpers inherit the dispatcher's lock without
fabricating locks they are never actually under).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Optional

from .asynclint import (
    QUEUE_BLOCKING_METHODS,
    QUEUE_CONSTRUCTORS,
    SOCKET_BLOCKING_METHODS,
    SOCKET_CONSTRUCTORS,
    AsyncEngine,
)
from .core import Finding, SourceFile
from .locklint import LOCK_TYPES, _dotted, _self_attr

__all__ = ["DeadlockEngine"]

# parameter / attribute names that mean "someone else's code"
CALLBACK_NAME_RE = re.compile(
    r"^(?:on_[a-z0-9_]+"
    r"|[a-z0-9_]*_(?:fn|fns|hook|hooks|cb|cbs|callback|callbacks"
    r"|probe|probes)"
    r"|fn|callback|hook|probe)$"
)

SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output",
                       "Popen"}

# interprocedural call-chain bound: deep enough for dispatcher ->
# helper -> other-class -> helper, cheap enough to stay O(methods)
MAX_CALL_DEPTH = 6


def _frame(src: SourceFile, node: ast.AST, desc: str) -> tuple:
    return (src, getattr(node, "lineno", 1), desc)


def _fmt_chain(chain: list[tuple]) -> str:
    return " -> ".join(
        f"{src.rel_path}:{line} {desc}" for src, line, desc in chain
    )


@dataclass
class _Acquire:
    lock: str            # canonical own-class lock attr
    node: ast.AST
    held: frozenset      # canonical own-class lock attrs held before


@dataclass
class _Call:
    kind: str            # "self" | "attr"
    recv: Optional[str]  # receiver attr for kind="attr"
    method: str
    node: ast.AST
    held: frozenset


@dataclass
class _Flag:
    rule: str
    node: ast.AST
    held: frozenset
    message: str         # may contain {lock}


@dataclass
class _CvEvent:
    kind: str            # "wait" | "wait_for" | "notify"
    attr: str            # the condition attribute (pre-canonical)
    node: ast.AST
    held: frozenset
    in_loop: bool
    timed: bool


class _FileCtx:
    """Per-file import/taint resolution shared by every class in it.
    One walk over the tree collects everything: asynclint's sleep/
    queue/socket taxonomy plus os/subprocess/threading resolution."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.os_aliases: set[str] = set()
        self.subprocess_aliases: set[str] = set()
        self.subprocess_names: set[str] = set()
        self.event_ctor_names: set[str] = set()
        self.threading_aliases: set[str] = {"threading"}
        self.lock_ctor_names: set[str] = set()
        self.time_aliases: set[str] = set()
        self.sleep_names: set[str] = set()
        self.queue_aliases: set[str] = set()
        self.socket_aliases: set[str] = set()
        self.queue_ctor_names: set[str] = set()
        self.socket_ctor_names: set[str] = set()
        self.queues: set[str] = set()    # names/attrs built from Queue()
        self.sockets: set[str] = set()
        assigns: list[ast.Assign] = []
        for node in src.walk():
            if isinstance(node, ast.Assign):
                assigns.append(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name
                    if a.name == "os":
                        self.os_aliases.add(alias)
                    elif a.name == "subprocess":
                        self.subprocess_aliases.add(alias)
                    elif a.name == "threading":
                        self.threading_aliases.add(alias)
                    elif a.name == "time":
                        self.time_aliases.add(alias)
                    elif a.name == "queue":
                        self.queue_aliases.add(alias)
                    elif a.name == "socket":
                        self.socket_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "subprocess":
                    for a in node.names:
                        if a.name in SUBPROCESS_BLOCKING:
                            self.subprocess_names.add(a.asname or a.name)
                elif node.module == "threading":
                    for a in node.names:
                        if a.name in LOCK_TYPES:
                            self.lock_ctor_names.add(a.asname or a.name)
                        elif a.name == "Event":
                            self.event_ctor_names.add(a.asname or a.name)
                elif node.module == "time":
                    for a in node.names:
                        if a.name == "sleep":
                            self.sleep_names.add(a.asname or a.name)
                elif node.module == "queue":
                    for a in node.names:
                        if a.name in QUEUE_CONSTRUCTORS:
                            self.queue_ctor_names.add(a.asname or a.name)
                elif node.module == "socket":
                    for a in node.names:
                        if a.name in SOCKET_CONSTRUCTORS:
                            self.socket_ctor_names.add(a.asname or a.name)
        for n in assigns:
            kind = self._ctor_kind(n.value)
            if kind is None:
                continue
            for t in n.targets:
                name = None
                if isinstance(t, ast.Name):
                    name = t.id
                elif isinstance(t, ast.Attribute):
                    name = t.attr       # self._q = Queue() taints "_q"
                if name is not None:
                    (self.queues if kind == "queue"
                     else self.sockets).add(name)

    def _ctor_kind(self, call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.queue_ctor_names:
                return "queue"
            if fn.id in self.socket_ctor_names:
                return "socket"
            return None
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            if fn.value.id in self.queue_aliases \
                    and fn.attr in QUEUE_CONSTRUCTORS:
                return "queue"
            if fn.value.id in self.socket_aliases \
                    and fn.attr in SOCKET_CONSTRUCTORS:
                return "socket"
        return None

    def is_sleep(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in self.sleep_names
        return (isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name)
                and f.value.id in self.time_aliases)

    def ctor_name(self, value: ast.AST) -> Optional[str]:
        """The dotted-last constructor name of ``X(...)`` / ``m.X(...)``,
        or None when the value is not a call on a name."""
        if not isinstance(value, ast.Call):
            return None
        parts = _dotted(value.func)
        return parts[-1] if parts else None

    def lock_kind(self, value: ast.AST) -> Optional[str]:
        """'Lock'/'RLock'/'Condition'/'Event' for a threading ctor call."""
        if not isinstance(value, ast.Call):
            return None
        parts = _dotted(value.func)
        if parts is None:
            return None
        # obs/scope.py instrumented drop-ins keep monitor semantics
        if parts[-1] == "TimedLock":
            return "RLock"
        if parts[-1] == "TimedCondition":
            return "Condition"
        if len(parts) == 1:
            if parts[0] in self.lock_ctor_names:
                return parts[0]
            if parts[0] in self.event_ctor_names:
                return "Event"
            return None
        if parts[0] in self.threading_aliases:
            if parts[-1] in LOCK_TYPES:
                return parts[-1]
            if parts[-1] == "Event":
                return "Event"
        return None


class _ClassInfo:
    def __init__(self, ctx: _FileCtx, node: ast.ClassDef):
        self.ctx = ctx
        self.src = ctx.src
        self.node = node
        self.name = node.name
        self.bases = [p[-1] for p in
                      (_dotted(b) for b in node.bases) if p]
        self.lock_attrs: set[str] = set()
        self.cond_attrs: set[str] = set()
        self.event_attrs: set[str] = set()
        self.alias: dict[str, str] = {}      # cv attr -> underlying lock
        self.owner: dict[str, str] = {}      # lock attr -> defining class
        self.attr_types: dict[str, str] = {}
        self.cb_attrs: set[str] = set()
        self.methods: dict[str, ast.FunctionDef] = {}
        self.scans: dict[str, "_MethodScan"] = {}
        self.entry_held: dict[str, frozenset] = {}
        self._collect()

    # -- collection --------------------------------------------------------
    def _collect(self) -> None:
        for m in self.node.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[m.name] = m
        for m in self.methods.values():
            cb_params = {a.arg for a in m.args.args + m.args.kwonlyargs
                         if CALLBACK_NAME_RE.match(a.arg)}
            # param annotations type peer attrs: __init__(self, reg:
            # "TenantRegistry") ... self._reg = reg
            ann: dict[str, str] = {}
            for a in m.args.args + m.args.kwonlyargs:
                if a.annotation is None:
                    continue
                t = a.annotation
                if isinstance(t, ast.Constant) and isinstance(t.value, str):
                    name = t.value.split(".")[-1].strip()
                    if name.isidentifier():
                        ann[a.arg] = name
                else:
                    parts = _dotted(t)
                    if parts:
                        ann[a.arg] = parts[-1]
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                kind = self.ctx.lock_kind(node.value)
                ctor = self.ctx.ctor_name(node.value)
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        # self._fns[key] = weight_fn taints the dict attr
                        if isinstance(t, ast.Subscript):
                            base = _self_attr(t.value)
                            if base is not None and isinstance(
                                node.value, ast.Name
                            ) and node.value.id in cb_params:
                                self.cb_attrs.add(base)
                        continue
                    if kind in ("Lock", "RLock", "Condition"):
                        self.lock_attrs.add(attr)
                        if kind == "Condition":
                            self.cond_attrs.add(attr)
                            # Condition(self._lock) /
                            # TimedCondition(name, lock=self._lock):
                            # holding either is holding both
                            cand = None
                            for kw in node.value.keywords:
                                if kw.arg == "lock":
                                    cand = kw.value
                            if cand is None:
                                args = node.value.args
                                idx = 1 if self.ctx.ctor_name(
                                    node.value) == "TimedCondition" else 0
                                if len(args) > idx:
                                    cand = args[idx]
                            if cand is not None:
                                under = _self_attr(cand)
                                if under is not None:
                                    self.alias[attr] = under
                    elif kind == "Event":
                        self.event_attrs.add(attr)
                    elif ctor is not None and ctor[:1].isupper():
                        self.attr_types.setdefault(attr, ctor)
                    elif isinstance(node.value, ast.Name) \
                            and node.value.id in ann:
                        self.attr_types.setdefault(
                            attr, ann[node.value.id])
                    if isinstance(node.value, ast.Name) \
                            and node.value.id in cb_params:
                        self.cb_attrs.add(attr)
                    if CALLBACK_NAME_RE.match(attr):
                        self.cb_attrs.add(attr)
        # aliases of non-locks are meaningless
        self.alias = {cv: lk for cv, lk in self.alias.items()
                      if lk in self.lock_attrs}
        for attr in self.lock_attrs:
            self.owner[attr] = self.name

    def canon(self, attr: str) -> str:
        """Canonical lock identity: a Condition built on another lock
        IS that lock for held/order purposes."""
        return self.alias.get(attr, attr)

    def qual(self, attr: str) -> str:
        c = self.canon(attr)
        return f"{self.owner.get(c, self.name)}.{c}"

    def inherit(self, ancestors: list["_ClassInfo"]) -> None:
        """Fold base-class state in: a subclass shares its parent's
        locks, conditions, aliases, typed attrs and callback attrs
        (``SharedBatcher`` guards with ``MicroBatcher``'s ``_cond``)."""
        for anc in ancestors:
            self.lock_attrs |= anc.lock_attrs
            self.cond_attrs |= anc.cond_attrs
            self.event_attrs |= anc.event_attrs
            self.cb_attrs |= anc.cb_attrs
            for cv, lk in anc.alias.items():
                self.alias.setdefault(cv, lk)
            for attr, owner in anc.owner.items():
                self.owner.setdefault(attr, owner)
            for attr, t in anc.attr_types.items():
                self.attr_types.setdefault(attr, t)

    # -- analysis ----------------------------------------------------------
    def scan(self, entry: dict[str, frozenset]) -> None:
        """(Re)scan every method, seeding each walker's running held
        set with the method's entry locks so explicit ``.release()``
        statements subtract inherited holds too (``MicroBatcher._lead``
        releases around the device call a lock it was CALLED with)."""
        if not (self.lock_attrs or self.cond_attrs):
            return
        self.entry_held = entry
        for name, m in self.methods.items():
            s = _MethodScan(self, m)
            s.run(entry.get(name, frozenset()))
            self.scans[name] = s


class _MethodScan:
    """Ordered walk of one method body tracking the running held set,
    including explicit ``.release()``/``.acquire()`` statements."""

    def __init__(self, cls: _ClassInfo, fn):
        self.cls = cls
        self.ctx = cls.ctx
        self.fn = fn
        self.acquires: list[_Acquire] = []
        self.calls: list[_Call] = []
        self.flags: list[_Flag] = []
        self.cv_events: list[_CvEvent] = []
        self.cb_locals: set[str] = {
            a.arg for a in fn.args.args + fn.args.kwonlyargs
            if CALLBACK_NAME_RE.match(a.arg)
        }

    def run(self, seed: frozenset = frozenset()) -> None:
        self._walk(self.fn.body, set(seed), in_loop=False)

    # -- helpers -----------------------------------------------------------
    def _held(self, held: set) -> frozenset:
        return frozenset(self.cls.canon(a) for a in held)

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.cls.lock_attrs:
            return self.cls.canon(attr)
        return None

    @staticmethod
    def _pruned(node: ast.AST):
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                    continue
                stack.append(c)

    def _mentions_cb_attr(self, expr: ast.AST) -> bool:
        for n in self._pruned(expr):
            a = _self_attr(n) if isinstance(n, ast.Attribute) else None
            if a is not None and a in self.cls.cb_attrs:
                return True
        return False

    @staticmethod
    def _untimed(call: ast.Call) -> bool:
        if call.args:
            return False
        return not any(kw.arg == "timeout" for kw in call.keywords)

    # -- statement walk ----------------------------------------------------
    def _walk(self, body: list, held: set, in_loop: bool) -> None:
        for stmt in body:
            self._stmt(stmt, held, in_loop)

    def _stmt(self, stmt: ast.stmt, held: set, in_loop: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.With):
            inner = set(held)
            for item in stmt.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self.acquires.append(_Acquire(
                        lock, item.context_expr, self._held(inner)))
                    inner.add(lock)
                else:
                    self._expr(item.context_expr, held, in_loop)
            self._walk(stmt.body, inner, in_loop)
            return
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                lock = self._lock_of(call.func.value)
                if lock is not None and call.func.attr == "acquire":
                    self.acquires.append(_Acquire(
                        lock, call, self._held(held)))
                    held.add(lock)
                    return
                if lock is not None and call.func.attr == "release":
                    held.discard(lock)
                    return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, held, in_loop)
            for h in stmt.handlers:
                self._walk(h.body, held, in_loop)
            self._walk(stmt.orelse, held, in_loop)
            self._walk(stmt.finalbody, held, in_loop)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, held, in_loop)
            self._walk(stmt.body, set(held), in_loop)
            self._walk(stmt.orelse, set(held), in_loop)
            return
        if isinstance(stmt, (ast.While,)):
            self._expr(stmt.test, held, in_loop)
            self._walk(stmt.body, held, in_loop=True)
            self._walk(stmt.orelse, held, in_loop)
            return
        if isinstance(stmt, ast.For):
            self._expr(stmt.iter, held, in_loop)
            # for fn in self._hooks: taints the loop variable
            if isinstance(stmt.target, ast.Name) \
                    and self._mentions_cb_attr(stmt.iter):
                self.cb_locals.add(stmt.target.id)
            self._walk(stmt.body, held, in_loop=True)
            self._walk(stmt.orelse, held, in_loop)
            return
        if isinstance(stmt, ast.Assign):
            # fn = self._weight_fns.get(tenant) taints the local
            for t in stmt.targets:
                if isinstance(t, ast.Name) \
                        and self._mentions_cb_attr(stmt.value):
                    self.cb_locals.add(t.id)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            self._expr(child, held, in_loop)

    # -- expression scan ---------------------------------------------------
    def _expr(self, node: ast.AST, held: set, in_loop: bool) -> None:
        h = self._held(held)
        for n in self._pruned(node):
            if isinstance(n, ast.Call):
                self._call(n, h, in_loop)

    def _call(self, call: ast.Call, held: frozenset,
              in_loop: bool) -> None:
        ctx = self.ctx
        cls = self.cls
        f = call.func
        # time.sleep / from time import sleep (asynclint resolution)
        if ctx.is_sleep(call):
            self.flags.append(_Flag(
                "PIO212", call, held,
                "time.sleep while holding {lock} makes every waiter "
                "eat the sleep — release first or move the wait to a "
                "timed Condition.wait"))
            return
        if isinstance(f, ast.Name):
            if f.id == "open":
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    "file I/O (open) while holding {lock}"))
                return
            if f.id in ctx.subprocess_names:
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    f"subprocess {f.id}() while holding {{lock}}"))
                return
            if f.id in self.cb_locals:
                self.flags.append(_Flag(
                    "PIO211", call, held,
                    f"user-supplied callable {f.id!r} invoked while "
                    "holding {lock} — the callee can take any lock or "
                    "block; call it after release"))
                return
            return
        if not isinstance(f, ast.Attribute):
            return
        parts = _dotted(f)
        if parts and len(parts) >= 2:
            if parts[0] in ctx.os_aliases and parts[-1] == "fsync":
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    "os.fsync while holding {lock} — a disk stall "
                    "blocks every thread behind the lock"))
                return
            if parts[0] in ctx.subprocess_aliases \
                    and parts[-1] in SUBPROCESS_BLOCKING:
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    f"subprocess.{parts[-1]}() while holding {{lock}}"))
                return
        self_attr = _self_attr(f)
        if self_attr is not None:
            # self.on_done(...): direct callback attr invocation
            if self_attr in cls.cb_attrs:
                self.flags.append(_Flag(
                    "PIO211", call, held,
                    f"user-supplied callable self.{self_attr} invoked "
                    "while holding {lock} — call it after release"))
            else:
                self.calls.append(_Call(
                    "self", None, self_attr, call, held))
            return
        recv_attr = _self_attr(f.value)
        meth = f.attr
        if recv_attr is not None:
            if recv_attr in cls.cond_attrs:
                if meth in ("wait", "wait_for"):
                    self.cv_events.append(_CvEvent(
                        meth, recv_attr, call, held, in_loop,
                        timed=not self._untimed(call)))
                    return
                if meth in ("notify", "notify_all"):
                    self.cv_events.append(_CvEvent(
                        "notify", recv_attr, call, held, in_loop,
                        timed=False))
                    return
            if recv_attr in cls.event_attrs and meth == "wait" \
                    and self._untimed(call):
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    f"untimed self.{recv_attr}.wait() while holding "
                    "{lock} — if the setter needs this lock, this "
                    "never wakes"))
                return
            if meth == "fsync":
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    f"self.{recv_attr}.fsync() while holding {{lock}}"))
                return
        # queue/socket taints (asynclint name- and attr-level)
        recv_name = recv_attr
        if recv_name is None and isinstance(f.value, ast.Name):
            recv_name = f.value.id
        if recv_name is not None:
            if recv_name in ctx.queues \
                    and meth in QUEUE_BLOCKING_METHODS \
                    and not AsyncEngine._has_nonblocking_kw(call):
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    f"untimed queue .{meth}() while holding {{lock}} — "
                    "if the peer needs this lock, this deadlocks"))
                return
            if recv_name in ctx.sockets \
                    and meth in SOCKET_BLOCKING_METHODS:
                self.flags.append(_Flag(
                    "PIO212", call, held,
                    f"blocking socket .{meth}() while holding {{lock}}"))
                return
        if recv_attr is not None:
            self.calls.append(_Call("attr", recv_attr, meth, call, held))


class DeadlockEngine:
    """Whole-program pass; hand it every SourceFile in scope at once
    (a single file is a one-file program — fixtures work unchanged)."""

    def __init__(self, srcs: list[SourceFile]):
        self.srcs = srcs
        self.findings: list[Finding] = []
        self.classes: list[_ClassInfo] = []
        # bare class name -> info; None marks an ambiguous (duplicate)
        # name we refuse to resolve through
        self.index: dict[str, Optional[_ClassInfo]] = {}
        self._acq_memo: dict[tuple[str, str], list] = {}

    def run(self) -> list[Finding]:
        for src in self.srcs:
            ctx = _FileCtx(src)
            for node in src.tree.body:
                if isinstance(node, ast.ClassDef):
                    info = _ClassInfo(ctx, node)
                    self.classes.append(info)
                    if info.name in self.index:
                        self.index[info.name] = None
                    else:
                        self.index[info.name] = info
        ancestors = {info.name: self._ancestors(info, {info.name})
                     for info in self.classes}
        for info in self.classes:
            info.inherit(ancestors[info.name])
        self._scan_to_fixpoint(ancestors)
        for info in self.classes:
            self._flag_class(info)
        self._lock_order()
        return self.findings

    def _ancestors(self, info: _ClassInfo, seen: set
                   ) -> list[_ClassInfo]:
        out: list[_ClassInfo] = []
        for base in info.bases:
            b = self.index.get(base)
            if b is not None and b.name not in seen:
                seen.add(b.name)
                out.append(b)
                out.extend(self._ancestors(b, seen))
        return out

    def _scan_to_fixpoint(self, ancestors: dict) -> None:
        """Iterate (scan with entry sets; recompute entry sets) until
        stable.  entry[m] = intersection of the ABSOLUTE held sets at
        every intra-class call site of m — own class and ancestors,
        since ``self.m()`` in a parent dispatches to the override
        (``MicroBatcher`` calls ``self._claim_locked()`` under
        ``_cond``; ``SharedBatcher._claim_locked`` runs lock-held).
        Methods nobody calls intra-class are API surface: unlocked.
        Starts from ∅ and grows one call-chain level per round, so the
        bound is the deepest helper chain, capped defensively."""
        entry: dict[str, dict[str, frozenset]] = {
            info.name: {} for info in self.classes
        }
        scanned: set[str] = set()
        for _ in range(10):
            for info in self.classes:
                # rescan only classes whose entry sets changed — most
                # converge immediately (all-∅ entries)
                if info.name in scanned \
                        and info.entry_held == entry[info.name]:
                    continue
                info.scan(entry[info.name])
                scanned.add(info.name)
            new: dict[str, dict[str, frozenset]] = {}
            for info in self.classes:
                sites: dict[str, list[frozenset]] = {}
                for holder in [info] + ancestors[info.name]:
                    for s in holder.scans.values():
                        for ev in s.calls:
                            if ev.kind == "self" \
                                    and ev.method in info.scans:
                                sites.setdefault(ev.method, []).append(
                                    ev.held)
                cur: dict[str, frozenset] = {}
                for name in info.scans:
                    if name == "__init__" or name not in sites:
                        cur[name] = frozenset()
                        continue
                    eff = sites[name][0]
                    for h in sites[name][1:]:
                        eff = eff & h
                    # only this class's own locks are meaningful seeds
                    cur[name] = eff & frozenset(
                        info.canon(a) for a in info.lock_attrs)
                new[info.name] = cur
            if new == entry:
                return
            entry = new

    # -- per-class rules (PIO211/212/213) ----------------------------------
    def _emit(self, src: SourceFile, rule: str, node: ast.AST,
              message: str, scope: str) -> None:
        f = src.finding(rule, node, message, scope)
        if f is not None:
            self.findings.append(f)

    def _flag_class(self, info: _ClassInfo) -> None:
        for name in sorted(info.scans):
            if name in ("__init__", "__new__", "__del__"):
                continue
            s = info.scans[name]
            scope = f"{info.name}.{name}"
            for fl in s.flags:
                if not fl.held:
                    continue
                lock = f"self.{sorted(fl.held)[0]}"
                self._emit(info.src, fl.rule, fl.node,
                           fl.message.format(lock=lock), scope)
            for ev in s.cv_events:
                eff = ev.held
                cv_lock = info.canon(ev.attr)
                if ev.kind == "notify":
                    if cv_lock not in eff:
                        self._emit(
                            info.src, "PIO213", ev.node,
                            f"self.{ev.attr}.notify() without holding "
                            f"self.{cv_lock} — the waiter can miss the "
                            "wakeup between its predicate check and its "
                            "wait()", scope)
                    continue
                if cv_lock not in eff:
                    self._emit(
                        info.src, "PIO213", ev.node,
                        f"self.{ev.attr}.{ev.kind}() without holding "
                        f"self.{cv_lock} (RuntimeError at runtime; "
                        "take the condition first)", scope)
                    continue
                if ev.kind == "wait" and not ev.timed and not ev.in_loop:
                    self._emit(
                        info.src, "PIO213", ev.node,
                        f"untimed self.{ev.attr}.wait() outside a "
                        "predicate loop — spurious wakeups and missed "
                        "notifies require `while not pred: cv.wait()`",
                        scope)

    # -- PIO210: lock-order graph ------------------------------------------
    def _resolve(self, info: _ClassInfo, ev: _Call
                 ) -> Optional[tuple[_ClassInfo, str]]:
        """(class, method) a call event dispatches to, when knowable."""
        if ev.kind == "self":
            return self._lookup_method(info, ev.method, set())
        tname = info.attr_types.get(ev.recv)
        if tname is None:
            return None
        target = self.index.get(tname)
        if target is None:
            return None
        return self._lookup_method(target, ev.method, set())

    def _lookup_method(self, info: _ClassInfo, method: str,
                       seen: set) -> Optional[tuple[_ClassInfo, str]]:
        if info.name in seen:
            return None
        seen.add(info.name)
        if method in info.scans:
            return (info, method)
        for base in info.bases:
            b = self.index.get(base)
            if b is not None:
                got = self._lookup_method(b, method, seen)
                if got is not None:
                    return got
        return None

    def _acquired_in(self, info: _ClassInfo, method: str,
                     depth: int, visiting: set) -> list[tuple[str, list]]:
        """Locks (qualified) acquired in ``method`` or transitively in
        resolvable callees, each with a witness chain of frames."""
        key = (info.name, method)
        if key in self._acq_memo:
            return self._acq_memo[key]
        if key in visiting or depth > MAX_CALL_DEPTH:
            return []
        visiting.add(key)
        out: dict[str, list] = {}
        s = info.scans.get(method)
        if s is not None:
            for a in s.acquires:
                q = info.qual(a.lock)
                out.setdefault(q, [_frame(
                    info.src, a.node,
                    f"{info.name}.{method} acquires {q}")])
            for ev in s.calls:
                target = self._resolve(info, ev)
                if target is None:
                    continue
                t_info, t_method = target
                frame = _frame(
                    info.src, ev.node,
                    f"{info.name}.{method} calls "
                    f"{t_info.name}.{t_method}")
                for q, chain in self._acquired_in(
                        t_info, t_method, depth + 1, visiting):
                    if q not in out or len(out[q]) > 1 + len(chain):
                        out[q] = [frame] + chain
        visiting.discard(key)
        result = sorted(out.items())
        if not visiting:
            # only outermost results are complete (an in-cycle result
            # is truncated by the visiting guard) — memoize just those
            self._acq_memo[key] = result
        return result

    def _lock_order(self) -> None:
        edges: dict[str, dict[str, list]] = {}

        def add_edge(a: str, b: str, chain: list) -> None:
            if a == b:
                return
            bucket = edges.setdefault(a, {})
            if b not in bucket or len(chain) < len(bucket[b]):
                bucket[b] = chain

        for info in self.classes:
            for name in sorted(info.scans):
                s = info.scans[name]
                for a in s.acquires:
                    q = info.qual(a.lock)
                    for h in a.held:
                        add_edge(info.qual(h), q, [_frame(
                            info.src, a.node,
                            f"{info.name}.{name} acquires {q} while "
                            f"holding {info.qual(h)}")])
                for ev in s.calls:
                    eff = ev.held
                    if not eff:
                        continue
                    target = self._resolve(info, ev)
                    if target is None:
                        continue
                    t_info, t_method = target
                    frame = _frame(
                        info.src, ev.node,
                        f"{info.name}.{name} calls "
                        f"{t_info.name}.{t_method}")
                    for q, chain in self._acquired_in(
                            t_info, t_method, 1, set()):
                        for h in eff:
                            add_edge(info.qual(h), q, [frame] + chain)

        reported: set[frozenset] = set()
        for a in sorted(edges):
            for b in sorted(edges[a]):
                path = self._find_path(edges, b, a)
                if path is None:
                    continue
                nodes = frozenset([a, b] + path)
                if nodes in reported:
                    continue
                reported.add(nodes)
                forward = edges[a][b]
                back_chain: list = []
                hops = [b] + path
                for i in range(len(hops) - 1):
                    back_chain += edges[hops[i]][hops[i + 1]]
                src, line, _ = forward[0]
                cyc = " -> ".join([a, b] + path)
                self._emit(
                    src, "PIO210", _Node(line),
                    f"lock-order inversion: {cyc}; "
                    f"path 1 [{a} then {b}]: {_fmt_chain(forward)}; "
                    f"path 2 [{b} back to {a}]: {_fmt_chain(back_chain)}"
                    " — pick one acquisition order",
                    "")

    @staticmethod
    def _find_path(edges: dict, start: str, goal: str
                   ) -> Optional[list[str]]:
        """BFS path start -> goal, returned as the node list AFTER
        start (ending with goal); None when unreachable."""
        from collections import deque

        prev: dict[str, str] = {}
        q = deque([start])
        seen = {start}
        while q:
            n = q.popleft()
            for m in edges.get(n, {}):
                if m in seen:
                    continue
                prev[m] = n
                if m == goal:
                    path = [goal]
                    while path[-1] != start:
                        path.append(prev[path[-1]])
                    return list(reversed(path))[1:]
                seen.add(m)
                q.append(m)
        return None


class _Node:
    """A minimal AST-node stand-in carrying just a location (cycle
    findings anchor to the first frame of their forward witness)."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.col_offset = 0
