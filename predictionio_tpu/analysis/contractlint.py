"""piolint contract-drift engine (PIO401–403): names that cross
process boundaries must exist on both sides.

Two string-typed contracts hold the observability story together and
fail only at smoke-runtime today, if at all:

* the **metric catalog** — every ``pio_*`` family the smoke tools,
  dashboards and docs grep out of ``/metrics`` must be registered (and
  carry the labels the reference selects on).  A renamed family breaks
  every dashboard silently; the smoke tool just stops matching.
* the **fault-point registry** — every point string handed to
  ``faults.check()``/``check_shard()``/``check_tenant()``/``fired()``
  or spelled inside a ``PIO_FAULT_PLAN`` example must be registered in
  ``resilience/faults.py``; an unregistered point makes a chaos test
  silently test nothing.

The engine is whole-program: it builds the catalog from the analyzed
file set (any ``.counter/.gauge/.histogram`` call whose first argument
is a ``"pio_..."`` literal, plus the module-level ``POINTS`` tuple),
then checks references in smoke tools — and, when the catalog source
``obs/__init__.py`` is in scope (i.e. a full-tree run), sweeps
``docs/*.md``, ``dashboards/``, and ``tests/*.py`` as plain text too.
Scoped runs (``--changed-files``) without the catalog in scope check
nothing rather than flagging every token: drift detection needs both
sides of the contract, and the gate's full-tree run always has them.

Reference grammar recognized (text-level, works in .py and .md alike):

* ``pio_family_name`` — must be a registered family (PIO401);
  exposition suffixes ``_bucket``/``_sum``/``_count`` normalize to the
  histogram family first.
* ``pio_family_name{label="x",other=~"y"}`` — every selected label key
  must be in the registered label set (PIO402); ``le``/``quantile``
  are always allowed (exposition-level labels).
* ``check("point.name")`` (and the shard/tenant/fired variants),
  ``FaultPlan.parse("p1:nth=2;p2")``, ``PIO_FAULT_PLAN=plan`` — every
  point must be registered (PIO403).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .core import _SUPPRESS_RE, Finding, SourceFile

__all__ = ["ContractEngine"]

REGISTER_METHODS = {"counter", "gauge", "histogram"}
# labels added below the registration layer: histogram exposition
# (le/quantile) and the tower merge's per-worker stamping (worker)
IMPLICIT_LABELS = {"le", "quantile", "worker"}
CATALOG_SOURCE = "predictionio_tpu/obs/__init__.py"

_METRIC_RE = re.compile(r"(?<![A-Za-z0-9_])pio_[a-z][a-z0-9_]*")
_LABEL_KEY_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_LABEL_ITEM_RE = re.compile(
    r"[A-Za-z_][A-Za-z0-9_]*(?:\s*=~?\s*[\"'][^\"']*[\"'])?"
)
_CHECK_RE = re.compile(
    r"\b(?:check|check_shard|check_tenant|fired|fired_shard)"
    r"\(\s*[\"']([a-z0-9_.]+)[\"']"
)
_PLAN_RE = re.compile(
    r"(?:\bFaultPlan\.parse\(\s*[\"']([^\"']+)[\"']"
    r"|\bPIO_FAULT_PLAN\s*[=:]\s*(?:[\"']([^\"']+)[\"']|([^\s\"'`]+)))"
)


def _plan_points(plan: str):
    """Point names a PIO_FAULT_PLAN string consults (parse grammar:
    ``;``-separated ``point:opt=v,...`` rules plus ``seed=N``)."""
    for rule in plan.split(";"):
        rule = rule.strip()
        if not rule or rule.startswith("seed="):
            continue
        point = rule.split(":", 1)[0].strip()
        if point and re.fullmatch(r"[a-z0-9_.]+", point) and "." in point:
            yield point


class ContractEngine:
    """Whole-program pass over the analyzed SourceFiles; pass
    ``smoke_scope=True`` to force reference checks on every file
    (fixture tests)."""

    def __init__(self, srcs: list[SourceFile], root: Path,
                 smoke_scope: bool = False):
        self.srcs = srcs
        self.root = root
        self.smoke_scope = smoke_scope
        self.findings: list[Finding] = []
        self.metrics: dict[str, set[str]] = {}
        self.points: set[str] = set()
        self.full_scope = False

    # -- catalog construction ----------------------------------------------
    def _index(self) -> None:
        for src in self.srcs:
            if src.rel_path == CATALOG_SOURCE:
                self.full_scope = True
            for node in src.walk():
                if isinstance(node, ast.Call):
                    self._register(node)
                elif isinstance(node, ast.Assign):
                    self._points_assign(node)

    def _register(self, call: ast.Call) -> None:
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in REGISTER_METHODS or not call.args:
            return
        arg0 = call.args[0]
        if not (isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
                and arg0.value.startswith("pio_")):
            return
        labels: set[str] = set()
        for kw in call.keywords:
            if kw.arg == "labels" and isinstance(
                    kw.value, (ast.Tuple, ast.List)):
                labels = {e.value for e in kw.value.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)}
        self.metrics.setdefault(arg0.value, set()).update(labels)

    def _points_assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "POINTS" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                self.points.update(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))

    # -- reference checks --------------------------------------------------
    def _emit(self, path: str, line_no: int, line: str, rule: str,
              col: int, message: str,
              src: Optional[SourceFile] = None) -> None:
        if src is not None:
            if src.suppressed(rule, line_no):
                return
        else:
            # swept text files get the same inline-suppression syntax
            m = _SUPPRESS_RE.search(line)
            if m is not None:
                codes = m.group("codes")
                if codes is None or rule in {
                        c.strip().upper() for c in codes.split(",")}:
                    return
        self.findings.append(Finding(
            rule=rule, path=path, line=line_no, col=col,
            message=message, scope="", snippet=line.strip()))

    def _check_metric_line(self, path: str, line_no: int, line: str,
                           src: Optional[SourceFile]) -> None:
        for m in _METRIC_RE.finditer(line):
            name = m.group(0)
            # construction prefixes (f"pio_hive_smoke_{n}", tmpdir
            # prefixes) and short non-metric identifiers (pio_pr
            # entity types, ~/.pio_tpu) are not references
            if name.endswith("_") or name.count("_") < 2:
                continue
            family = name
            if family not in self.metrics:
                for suffix in ("_bucket", "_sum", "_count"):
                    if family.endswith(suffix) \
                            and family[: -len(suffix)] in self.metrics:
                        family = family[: -len(suffix)]
                        break
            if family not in self.metrics:
                # grep-for-prefix is a legitimate reference idiom:
                # `grep pio_query_latency` still matches the family
                if any(reg.startswith(family) for reg in self.metrics):
                    continue
                self._emit(path, line_no, line, "PIO401", m.start(),
                           f"metric family {name!r} is not registered "
                           "in the obs catalog — rename the reference "
                           "or register the family", src)
                continue
            rest = line[m.end():]
            if not rest.startswith("{"):
                continue
            close = rest.find("}")
            if close < 0:
                continue
            items = [i.strip() for i in rest[1:close].split(",")]
            # only a well-formed selector is a label contract; prose
            # globs like {als.user_half|als.item_half} are not
            if not all(_LABEL_ITEM_RE.fullmatch(i) for i in items):
                continue
            allowed = self.metrics[family] | IMPLICIT_LABELS
            for item in items:
                key = _LABEL_KEY_RE.match(item)
                if key and key.group(0) not in allowed:
                    self._emit(
                        path, line_no, line, "PIO402", m.start(),
                        f"metric {family!r} has no label "
                        f"{key.group(0)!r} (registered: "
                        f"{sorted(self.metrics[family]) or 'none'})",
                        src)

    def _check_fault_line(self, path: str, line_no: int, line: str,
                          src: Optional[SourceFile]) -> None:
        refs: list[tuple[int, str]] = []
        for m in _CHECK_RE.finditer(line):
            # registered points are dotted (storage.write); dotless
            # strings are some local helper's argument, not a fault ref
            if "." in m.group(1):
                refs.append((m.start(), m.group(1)))
        for m in _PLAN_RE.finditer(line):
            plan = m.group(1) or m.group(2) or m.group(3) or ""
            refs.extend((m.start(), p) for p in _plan_points(plan))
        for col, point in refs:
            if point not in self.points:
                self._emit(path, line_no, line, "PIO403", col,
                           f"fault point {point!r} is not registered in "
                           "resilience/faults.py POINTS — chaos hooks "
                           "on unknown points never fire", src)

    def _scan_text(self, path: str, text: str,
                   src: Optional[SourceFile] = None,
                   metrics_too: bool = True) -> None:
        for i, line in enumerate(text.splitlines(), start=1):
            if self.metrics and metrics_too:
                self._check_metric_line(path, i, line, src)
            if self.points:
                self._check_fault_line(path, i, line, src)

    def _is_smoke(self, src: SourceFile) -> bool:
        if self.smoke_scope:
            return True
        parts = src.rel_path.split("/")
        return (parts[0] == "tools"
                and parts[-1].endswith("_smoke.py"))

    def run(self) -> list[Finding]:
        self._index()
        if not (self.metrics or self.points):
            return self.findings
        for src in self.srcs:
            if self._is_smoke(src):
                self._scan_text(src.rel_path, src.text, src)
        if not self.full_scope:
            return self.findings
        # full-tree run: sweep docs and dashboards for both contracts,
        # tests for fault points only (tests register throwaway pio_*
        # families of their own; the ISSUE contract for tests is the
        # fault-point registry) — all as plain text
        sweep: list[tuple[Path, bool]] = []
        for pattern in ("docs/*.md", "dashboards/**/*"):
            sweep.extend((p, True) for p in self.root.glob(pattern))
        sweep.extend(
            (p, False) for p in self.root.glob("tests/*.py"))
        for p, metrics_too in sorted(sweep):
            if not p.is_file():
                continue
            try:
                rel = p.relative_to(self.root).as_posix()
            except ValueError:
                rel = p.as_posix()
            try:
                self._scan_text(rel, p.read_text(),
                                metrics_too=metrics_too)
            except (OSError, UnicodeDecodeError):
                continue
        return self.findings
