"""piolint event-loop engine (PIO110): blocking calls on loop threads.

The pio-surge serving edge multiplexes every connection through ONE
selector loop (`server/eventloop.py`); a single blocking call inside a
loop-thread handler stalls every in-flight request at once — the
precise failure mode the event-loop rework exists to remove.  Loop-
thread code is marked: functions carrying the
``@callback_scope`` decorator (``server/eventloop.callback_scope`` —
identity at runtime, a contract for this engine), plus every ``async
def`` coroutine (awaiting blocking calls stalls the asyncio loop the
same way).

Inside that scope the engine flags:

* ``time.sleep(...)`` — resolved through import aliases like the other
  engines (``import time as t`` / ``from time import sleep``);
* blocking socket I/O — ``.recv/.send/.sendall/.accept/.connect`` on a
  name assigned from ``socket.socket(...)`` or
  ``socket.create_connection(...)`` (the taint is deliberately
  name-based and local: the loop core's own non-blocking sockets live
  in unmarked helper methods);
* ``queue.Queue``/``SimpleQueue`` ``.get()``/``.put()`` without a
  ``timeout=`` keyword (and without ``block=False``) on a name
  assigned from a queue constructor — an untimed get parks the loop
  forever if the producer died.

Deliberately NOT flagged: ``selector.select(...)`` (the loop's own
bounded wait), monotonic reads, lock acquisitions (PIO2xx territory),
and anything in nested ``def``s — an inner function defined inside a
callback is deferred work (aux pool / dispatcher), not loop-thread
code.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

__all__ = ["AsyncEngine"]

SOCKET_BLOCKING_METHODS = {"recv", "recv_into", "send", "sendall",
                           "accept", "connect", "makefile"}
QUEUE_BLOCKING_METHODS = {"get", "put"}
QUEUE_CONSTRUCTORS = {"Queue", "SimpleQueue", "LifoQueue",
                      "PriorityQueue"}
SOCKET_CONSTRUCTORS = {"socket", "create_connection"}
MARKER_DECORATORS = {"callback_scope", "loop_callback"}


def _decorator_name(d: ast.AST) -> str:
    if isinstance(d, ast.Call):
        d = d.func
    if isinstance(d, ast.Name):
        return d.id
    if isinstance(d, ast.Attribute):
        return d.attr
    return ""


class AsyncEngine:
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []
        # import resolution: module aliases + from-imports
        self.time_aliases: set[str] = set()
        self.queue_aliases: set[str] = set()
        self.socket_aliases: set[str] = set()
        self.sleep_names: set[str] = set()
        self.queue_ctor_names: set[str] = set()
        self.socket_ctor_names: set[str] = set()
        for node in src.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name
                    if a.name == "time":
                        self.time_aliases.add(alias)
                    elif a.name == "queue":
                        self.queue_aliases.add(alias)
                    elif a.name == "socket":
                        self.socket_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for a in node.names:
                        if a.name == "sleep":
                            self.sleep_names.add(a.asname or a.name)
                elif node.module == "queue":
                    for a in node.names:
                        if a.name in QUEUE_CONSTRUCTORS:
                            self.queue_ctor_names.add(a.asname or a.name)
                elif node.module == "socket":
                    for a in node.names:
                        if a.name in SOCKET_CONSTRUCTORS:
                            self.socket_ctor_names.add(a.asname or a.name)
        # module-level taints (a loop class often builds its queue in
        # __init__ and drains it in a marked callback — attribute
        # taints are tracked per class too, conservatively by name)
        self.module_queues, self.module_sockets = self._taints(src.tree)

    # -- taint collection --------------------------------------------------
    def _ctor_kind(self, call: ast.AST):
        """'queue' | 'socket' | None for a constructor call node."""
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.queue_ctor_names:
                return "queue"
            if fn.id in self.socket_ctor_names:
                return "socket"
            return None
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            if (fn.value.id in self.queue_aliases
                    and fn.attr in QUEUE_CONSTRUCTORS):
                return "queue"
            if (fn.value.id in self.socket_aliases
                    and fn.attr in SOCKET_CONSTRUCTORS):
                return "socket"
        return None

    @staticmethod
    def _target_names(target: ast.AST):
        """Name or self.attr assignment targets as taintable strings."""
        if isinstance(target, ast.Name):
            yield target.id
        elif isinstance(target, ast.Attribute):
            yield target.attr  # self._q = Queue() taints "_q"

    def _taints(self, scope: ast.AST) -> tuple[set[str], set[str]]:
        queues: set[str] = set()
        sockets: set[str] = set()
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign):
                kind = self._ctor_kind(n.value)
                if kind is None:
                    continue
                for t in n.targets:
                    for name in self._target_names(t):
                        (queues if kind == "queue" else sockets).add(name)
        return queues, sockets

    # -- scope walk --------------------------------------------------------
    @staticmethod
    def _own_nodes(scope: ast.AST):
        """Walk without descending into nested defs: an inner function
        is deferred work, not loop-thread code."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _in_scope_functions(self):
        for node in self.src.walk():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.AsyncFunctionDef):
                yield node, "coroutine"
                continue
            if any(_decorator_name(d) in MARKER_DECORATORS
                   for d in node.decorator_list):
                yield node, "@callback_scope"

    def run(self) -> list[Finding]:
        for fn, kind in self._in_scope_functions():
            q_taint, s_taint = self._taints(fn)
            q_taint |= self.module_queues
            s_taint |= self.module_sockets
            for n in self._own_nodes(fn):
                if not isinstance(n, ast.Call):
                    continue
                self._check_call(n, fn.name, kind, q_taint, s_taint)
        return self.findings

    # -- checks ------------------------------------------------------------
    def _is_sleep(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in self.sleep_names
        return (isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name)
                and f.value.id in self.time_aliases)

    @staticmethod
    def _receiver(call: ast.Call):
        """The name a method call's receiver resolves to: ``q.get()``
        -> 'q'; ``self._q.get()`` -> '_q'."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None, None
        v = f.value
        if isinstance(v, ast.Name):
            return v.id, f.attr
        if isinstance(v, ast.Attribute):
            return v.attr, f.attr
        return None, None

    @staticmethod
    def _has_nonblocking_kw(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "timeout":
                return True
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        return False

    def _check_call(self, call: ast.Call, scope: str, kind: str,
                    q_taint: set, s_taint: set) -> None:
        if self._is_sleep(call):
            self._flag(call, scope,
                       f"time.sleep inside {kind} {scope!r} stalls every "
                       "connection on the loop — defer to the aux pool "
                       "or schedule a wakeup instead")
            return
        recv, meth = self._receiver(call)
        if recv is None:
            return
        if recv in q_taint and meth in QUEUE_BLOCKING_METHODS \
                and not self._has_nonblocking_kw(call):
            self._flag(call, scope,
                       f"queue .{meth}() without timeout inside {kind} "
                       f"{scope!r}: if the producer died this parks the "
                       "loop forever — pass timeout= or block=False")
        elif recv in s_taint and meth in SOCKET_BLOCKING_METHODS:
            self._flag(call, scope,
                       f"blocking socket .{meth}() inside {kind} "
                       f"{scope!r}: loop-thread sockets must be "
                       "non-blocking and selector-driven")

    def _flag(self, node: ast.AST, scope: str, message: str) -> None:
        f = self.src.finding("PIO110", node, message, scope)
        if f is not None:
            self.findings.append(f)
