"""Shared piolint infrastructure: rule table, findings, inline
suppressions, and the accepted-findings baseline.

Baseline identity is ``(path, rule, scope, snippet)`` — deliberately
NOT the line number, so unrelated edits above a known finding don't
churn `piolint.baseline.json`; moving or editing the flagged line
itself surfaces it again for re-review.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

__all__ = [
    "RULES",
    "Finding",
    "SourceFile",
    "Baseline",
    "load_baseline",
]

# code -> one-line rule description (docs/ARCHITECTURE.md renders the
# same table; tests assert every code here has fixture coverage)
RULES: dict[str, str] = {
    "PIO100": "file in the gate scope does not parse",
    "PIO101": "host-device sync: .item()/.tolist() on a traced value "
              "inside jit-traced code",
    "PIO102": "host-device sync: float()/int()/bool() forcing a traced "
              "value inside jit-traced code",
    "PIO103": "host-device sync: numpy np.asarray/np.array on a traced "
              "value inside jit-traced code",
    "PIO104": "trace/recompile hazard: Python if/while/assert branching "
              "on a traced value",
    "PIO105": "recompile hazard: unhashable literal (list/dict/set) "
              "bound to a static jit argument",
    "PIO106": "trace-constant leak: string formatting (f-string/str/"
              "repr/format) of a traced value",
    "PIO107": "donated buffer reused after a donating jit call",
    "PIO108": "timing lie: time.* span over device work without a "
              "fence/block_until_ready (bench*/tools only)",
    "PIO109": "wall-clock duration: time.time() t0/dt subtraction — "
              "use monotonic()/perf_counter() (predictionio_tpu/ only)",
    "PIO110": "event-loop stall: blocking call (time.sleep, blocking "
              "socket I/O, untimed queue get/put) inside a coroutine "
              "or @callback_scope loop-thread function",
    "PIO201": "lock discipline: write to a lock-guarded attribute "
              "without holding the lock",
    "PIO202": "lock discipline: read of a lock-guarded attribute "
              "without holding the lock",
    "PIO203": "lock discipline: manual .acquire() without a matching "
              "try/finally release",
    "PIO210": "deadlock hazard: lock-order inversion — two locks are "
              "acquired in opposite orders on different interprocedural "
              "paths (both witness paths printed)",
    "PIO211": "callback under lock: a user-supplied callable (on_done, "
              "weight_fn, batch_fn, fault hooks, ...) is invoked while "
              "a lock is statically held — the callee can take any "
              "lock or block, wedging every thread behind this one",
    "PIO212": "blocking under lock: time.sleep, socket/file I/O, fsync, "
              "subprocess, untimed Queue.get/put, or untimed "
              "Event.wait() inside a lock-held region",
    "PIO213": "condition-variable discipline: wait() outside a "
              "predicate loop, or notify()/notify_all() without "
              "holding the condition's lock",
    "PIO301": "engine isolation: an engine template file imports "
              "server internals (predictionio_tpu.server) — engines "
              "declare components, the platform owns serving "
              "(templates/*.py excluding _-prefixed infra)",
    "PIO401": "contract drift: a pio_* metric family name referenced "
              "in smoke tools/dashboards/docs is not registered in "
              "the obs catalog",
    "PIO402": "contract drift: a pio_* metric reference names a label "
              "the registered family does not carry",
    "PIO403": "contract drift: a fault-point string (faults.check/"
              "check_shard/check_tenant/fired or a PIO_FAULT_PLAN "
              "example) is not registered in resilience/faults.py",
}


@dataclass
class Finding:
    rule: str
    path: str           # repo-relative posix path
    line: int
    col: int
    message: str
    scope: str          # qualname of the enclosing function/class ('' = module)
    snippet: str        # stripped source line (baseline identity)
    baselined: bool = False

    def identity(self) -> tuple[str, str, str, str]:
        return (self.path, self.rule, self.scope, self.snippet)

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "scope": self.scope,
            "snippet": self.snippet,
            "baselined": self.baselined,
        }

    def text(self) -> str:
        where = f" [{self.scope}]" if self.scope else ""
        tag = " (baselined)" if self.baselined else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}{where}{tag}")


_SUPPRESS_RE = re.compile(
    r"#\s*piolint:\s*disable(?:=(?P<codes>[A-Za-z0-9_,\s]+))?"
)


class SourceFile:
    """One parsed source file + its inline suppressions."""

    def __init__(self, path: Path, rel_path: str, text: str):
        self.path = path
        self.rel_path = rel_path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        self._walk_cache: Optional[list] = None
        # line -> set of suppressed codes; the sentinel "*" means all
        self.suppressions: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            codes = m.group("codes")
            if codes is None:
                self.suppressions[i] = {"*"}
            else:
                self.suppressions[i] = {
                    c.strip().upper() for c in codes.split(",") if c.strip()
                }

    @classmethod
    def load(cls, path: Path, root: Path) -> "SourceFile":
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        return cls(path, rel, path.read_text())

    def walk(self) -> list:
        """Cached flat preorder walk of the whole tree.  Every engine
        iterates the full module at least once; one traversal serves
        them all (the list is read-only by convention)."""
        if self._walk_cache is None:
            self._walk_cache = list(ast.walk(self.tree))
        return self._walk_cache

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, rule: str, line: int) -> bool:
        codes = self.suppressions.get(line)
        if not codes:
            return False
        return "*" in codes or rule.upper() in codes

    def finding(self, rule: str, node: ast.AST, message: str,
                scope: str = "") -> Optional[Finding]:
        """Build a Finding unless an inline comment suppresses it."""
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.suppressed(rule, line):
            return None
        return Finding(
            rule=rule, path=self.rel_path, line=line, col=col,
            message=message, scope=scope, snippet=self.snippet(line),
        )


@dataclass
class Baseline:
    """Accepted findings: the debt ledger the gate tolerates.

    Each entry carries a one-line ``justification`` — a baseline entry
    without a reason is just a muted bug.
    """

    entries: list[dict] = field(default_factory=list)

    def _keys(self) -> set[tuple[str, str, str, str]]:
        return {
            (e.get("path", ""), e.get("rule", ""), e.get("scope", ""),
             e.get("snippet", ""))
            for e in self.entries
        }

    def apply(self, findings: list[Finding]) -> None:
        """Mark findings that match a baseline entry."""
        keys = self._keys()
        for f in findings:
            f.baselined = f.identity() in keys

    @classmethod
    def from_findings(cls, findings: list[Finding],
                      justification: str = "accepted by --write-baseline",
                      ) -> "Baseline":
        seen: set[tuple] = set()
        entries = []
        for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line)):
            if f.identity() in seen:
                continue
            seen.add(f.identity())
            entries.append({
                "path": f.path, "rule": f.rule, "scope": f.scope,
                "snippet": f.snippet, "justification": justification,
            })
        return cls(entries=entries)

    def save(self, path: Path) -> None:
        path.write_text(json.dumps(
            {"version": 1, "entries": self.entries}, indent=2,
        ) + "\n")


def load_baseline(path: Optional[Path]) -> Baseline:
    if path is None or not path.exists():
        return Baseline()
    data = json.loads(path.read_text())
    return Baseline(entries=list(data.get("entries", [])))
