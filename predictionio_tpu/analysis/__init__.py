"""piolint — JAX-aware static analysis + lock-discipline checking.

Three AST engines over the package's own source (no imports, no jax, no
device): the **JAX engine** (PIO1xx, `jaxlint.py`) walks functions
reachable from ``jax.jit``/``pjit``/``shard_map`` tracing and flags
host-device syncs, recompile hazards, donated-buffer reuse, and
unfenced benchmark timing spans; the **concurrency engine** (PIO2xx,
`locklint.py`) infers per-class lock discipline — which ``self._*``
attributes are ever written under ``self._lock`` — and flags accesses
on paths that don't hold the lock; the **clock engine** (PIO109,
`timelint.py`) flags wall-clock ``time.time()`` t0/dt subtractions in
``predictionio_tpu/`` — durations must come from monotonic clocks.

Driver: ``python -m predictionio_tpu.analysis`` (see `cli.py`).
Findings are suppressed inline with ``# piolint: disable=PIO101`` or
accepted wholesale in ``piolint.baseline.json`` (matched by
path/rule/scope/snippet, so line drift doesn't churn the baseline).
``tools/gate.sh`` and ``tools/pre-commit`` fail on any non-baseline
finding.
"""

from .cli import analyze_file, analyze_paths, main
from .core import (
    RULES,
    Baseline,
    Finding,
    SourceFile,
    load_baseline,
)

__all__ = [
    "RULES",
    "Baseline",
    "Finding",
    "SourceFile",
    "load_baseline",
    "analyze_file",
    "analyze_paths",
    "main",
]
