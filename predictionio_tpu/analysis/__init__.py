"""piolint — JAX-aware static analysis, lock-discipline, deadlock, and
contract-drift checking.

AST engines over the package's own source (no imports, no jax, no
device).  Per-file: the **JAX engine** (PIO101–108, `jaxlint.py`) walks
functions reachable from ``jax.jit``/``pjit``/``shard_map`` tracing and
flags host-device syncs, recompile hazards, donated-buffer reuse, and
unfenced benchmark timing spans; the **clock engine** (PIO109,
`timelint.py`) flags wall-clock ``time.time()`` t0/dt subtractions in
``predictionio_tpu/``; the **event-loop engine** (PIO110,
`asynclint.py`) flags blocking calls inside coroutines; the **lock
engine** (PIO201–203, `locklint.py`) infers per-class lock discipline
and flags off-lock accesses; the **engine-isolation engine** (PIO301,
`enginelint.py`) keeps templates off server internals.

Whole-program (run once over the full analyzed set): the **deadlock
engine** (PIO210–213, `deadlint.py`) builds a cross-class lock-order
graph via a bounded-depth interprocedural walk and flags lock-order
inversions (with both witness paths), callbacks invoked under a lock,
blocking calls in lock-held regions, and condition-variable misuse;
the **contract engine** (PIO401–403, `contractlint.py`) checks that
``pio_*`` metric families / labels and fault-point strings referenced
by smoke tools, dashboards, docs, and tests exist in the obs catalog
and the resilience fault registry.

Driver: ``python -m predictionio_tpu.analysis`` (see `cli.py`; also
``--format sarif`` for annotators).  Findings are suppressed inline
with ``# piolint: disable=PIO101`` or accepted wholesale in
``piolint.baseline.json`` (matched by path/rule/scope/snippet, so line
drift doesn't churn the baseline; deadlock entries additionally carry
a written ``justification`` that ``--strict`` enforces).
``tools/gate.sh`` and ``tools/pre-commit`` fail on any non-baseline
finding.
"""

from .cli import analyze_file, analyze_paths, main
from .core import (
    RULES,
    Baseline,
    Finding,
    SourceFile,
    load_baseline,
)

__all__ = [
    "RULES",
    "Baseline",
    "Finding",
    "SourceFile",
    "load_baseline",
    "analyze_file",
    "analyze_paths",
    "main",
]
