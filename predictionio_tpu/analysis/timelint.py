"""piolint clock engine (PIO109): wall-clock durations.

``time.time()`` answers "what time is it", not "how long did that
take": NTP slews, DST-less-but-steppable system clocks, and VM
migrations all make a ``time.time() - t0`` delta lie by arbitrary
amounts in either direction.  Inside ``predictionio_tpu/`` every
duration must come from ``time.monotonic()`` or ``time.perf_counter()``
(the discipline ``server/microbatch.py`` always followed and
``server/serving.py`` was migrated to); ``time.time()`` remains correct
for *timestamps* — ``start_time`` fields, hour bucketing, records that
must be comparable across machines.

Detection is the t0/dt subtraction pattern, kept deliberately narrow so
timestamps stay legal:

* a name assigned from a wall-clock call (``t0 = time.time()``) is
  *wall-tainted* within its scope (module body or one function);
* a ``BinOp(Sub)`` whose BOTH operands are wall-clock — a direct
  ``time.time()`` call or a wall-tainted name — is a finding.

``time.time() - age_s`` (deriving a cutoff timestamp) and
``time.time() > deadline`` (comparisons) are not flagged: one operand
is not wall-clock / not a subtraction.  The driver runs this engine on
``predictionio_tpu/`` files only; bench harnesses and tools keep their
wall clocks (their spans are fenced and coarse — PIO108's territory).
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, SourceFile

__all__ = ["TimeEngine"]

WALL_FUNCS = {"time"}  # time.time() — the only steppable clock in `time`


class TimeEngine:
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []
        # import resolution: `import time [as t]` / `from time import
        # time [as now]`
        self.time_aliases: set[str] = set()
        self.wall_names: set[str] = set()
        for node in src.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        self.time_aliases.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in WALL_FUNCS:
                        self.wall_names.add(a.asname or a.name)

    def run(self) -> list[Finding]:
        scopes: list[tuple[ast.AST, str]] = [(self.src.tree, "")]
        for node in self.src.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node, node.name))
        for scope, name in scopes:
            self._check_scope(scope, name)
        return self.findings

    # -- helpers -----------------------------------------------------------
    def _is_wall_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        fn = node.func
        if isinstance(fn, ast.Name):
            return fn.id in self.wall_names
        if isinstance(fn, ast.Attribute) and fn.attr in WALL_FUNCS \
                and isinstance(fn.value, ast.Name):
            return fn.value.id in self.time_aliases
        return False

    @staticmethod
    def _own_nodes(scope: ast.AST):
        """Walk ``scope`` without descending into nested functions —
        a nested def's ``t0`` is a different variable."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _check_scope(self, scope: ast.AST, scope_name: str) -> None:
        nodes = list(self._own_nodes(scope))
        tainted: set[str] = set()
        for n in nodes:
            if isinstance(n, ast.Assign) and self._is_wall_call(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)

        def wallish(side: ast.AST) -> Optional[str]:
            if self._is_wall_call(side):
                return "time.time()"
            if isinstance(side, ast.Name) and side.id in tainted:
                return side.id
            return None

        for n in nodes:
            if not (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)):
                continue
            left, right = wallish(n.left), wallish(n.right)
            if left is not None and right is not None:
                f = self.src.finding(
                    "PIO109", n,
                    f"duration computed from wall clocks ({left} - "
                    f"{right}): time.time() can step backwards/forwards "
                    "under NTP — use time.perf_counter() or "
                    "time.monotonic() for deltas (wall clock stays "
                    "correct for timestamps)",
                    scope_name,
                )
                if f is not None:
                    self.findings.append(f)
