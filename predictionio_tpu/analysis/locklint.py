"""piolint concurrency engine (PIO2xx): per-class lock discipline.

The discipline is *inferred*, not declared: for every class that owns a
``threading.Lock``/``RLock``/``Condition`` attribute, any ``self._*``
attribute that is ever WRITTEN while holding that lock is treated as
lock-guarded, and every read or write of it on a code path that does
not hold the lock is a finding.  This is exactly the invariant the
drain-thread / serving-reload / stats-counter code means to maintain
but no example-based test can check: the interleaving that breaks it
may need two threads to hit a three-instruction window.

Refinements that keep the false-positive rate workable:

* ``__init__``/``__del__`` are exempt (construction and teardown
  happen-before/after sharing);
* a helper method whose every intra-class call site holds the lock is
  analyzed as lock-held itself (``StatsCollector._roll``,
  ``MicroBatcher._lead``), computed to fixpoint;
* container mutation through method calls (``self._dq.append(...)``,
  ``self.counts.update(...)``) counts as a write, since those are the
  shared-state mutations that matter for dict/deque/Counter attrs;
* nested function and class bodies inside a method are skipped — they
  execute on other threads or at other times, so the enclosing
  ``with self._lock`` proves nothing about them.

PIO203 flags manual ``.acquire()`` calls that are not immediately
followed by a ``try``/``finally`` release and are not themselves inside
a ``finally`` block (the release-around-device-call re-acquire idiom).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from .core import Finding, SourceFile

__all__ = ["LockEngine"]

LOCK_TYPES = {"Lock", "RLock", "Condition"}
# obs/scope.py instrumented drop-ins: same monitor semantics, so an
# attr built from one IS a lock for guard-discipline purposes
TIMED_LOCK_TYPES = {"TimedLock", "TimedCondition"}

# method calls on an attribute that mutate the underlying container
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "remove", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "popitem",
}


def _dotted(node: ast.AST) -> Optional[list[str]]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> "X" (also accepts ``cls.X``)."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.attr
    return None


@dataclass
class _Access:
    attr: str
    write: bool
    node: ast.AST
    held: frozenset  # lock attrs held at this point


@dataclass
class _CallSite:
    method: str
    held: frozenset


class LockEngine:
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []
        self.threading_aliases: set[str] = {"threading"}
        self.lock_ctor_names: set[str] = set()  # from threading import Lock
        for node in src.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        self.threading_aliases.add(a.asname or "threading")
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for a in node.names:
                    if a.name in LOCK_TYPES:
                        self.lock_ctor_names.add(a.asname or a.name)

    def run(self) -> list[Finding]:
        for node in self.src.tree.body:
            if isinstance(node, ast.ClassDef):
                self._analyze_class(node)
        return self.findings

    def _emit(self, rule: str, node: ast.AST, message: str,
              scope: str) -> None:
        f = self.src.finding(rule, node, message, scope)
        if f is not None:
            self.findings.append(f)

    def _is_lock_ctor(self, value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        parts = _dotted(value.func)
        if parts is None:
            return False
        if parts[-1] in TIMED_LOCK_TYPES:
            return True
        if len(parts) == 1:
            return parts[0] in self.lock_ctor_names
        return (parts[0] in self.threading_aliases
                and parts[-1] in LOCK_TYPES)

    # -- per-class ---------------------------------------------------------
    def _analyze_class(self, cls: ast.ClassDef) -> None:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        if not methods:
            return
        # 1) which self attrs are locks
        lock_attrs: set[str] = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) \
                        and self._is_lock_ctor(node.value):
                    for t in node.targets:
                        a = _self_attr(t)
                        if a is not None:
                            lock_attrs.add(a)
        if not lock_attrs:
            return

        # 2) scan each method: accesses, call sites, acquire() discipline
        scans = {
            m.name: _MethodScan(self, cls.name, m, lock_attrs)
            for m in methods
        }
        for s in scans.values():
            s.run()

        # 3) fixpoint: methods whose every intra-class call site holds a
        # lock are lock-held throughout (>=1 call site required; __init__
        # call sites count as unlocked — it IS unlocked)
        held_methods: set[str] = set()
        changed = True
        while changed:
            changed = False
            callers: dict[str, list[_CallSite]] = {}
            for name, s in scans.items():
                for cs in s.calls:
                    eff = cs.held or (
                        frozenset(lock_attrs) if name in held_methods
                        else frozenset()
                    )
                    callers.setdefault(cs.method, []).append(
                        _CallSite(cs.method, eff)
                    )
            for name in scans:
                if name in held_methods or name == "__init__":
                    continue
                sites = callers.get(name, [])
                if sites and all(cs.held for cs in sites):
                    held_methods.add(name)
                    changed = True

        # 4) guarded set: attrs written under a lock anywhere
        guarded: dict[str, str] = {}  # attr -> lock attr that guards it
        for name, s in scans.items():
            base = (frozenset(lock_attrs) if name in held_methods
                    else frozenset())
            for acc in s.accesses:
                held = acc.held or base
                if acc.write and held and acc.attr not in lock_attrs:
                    guarded.setdefault(acc.attr, sorted(held)[0])

        # 5) violations: guarded-attr access with no lock held
        for name, s in scans.items():
            if name in ("__init__", "__new__", "__del__"):
                continue
            base = (frozenset(lock_attrs) if name in held_methods
                    else frozenset())
            for acc in s.accesses:
                if acc.attr not in guarded:
                    continue
                if acc.held or base:
                    continue
                lock = guarded[acc.attr]
                kind = "write to" if acc.write else "read of"
                rule = "PIO201" if acc.write else "PIO202"
                self._emit(
                    rule, acc.node,
                    f"{kind} {acc.attr!r} without holding self.{lock} "
                    f"(attribute is written under self.{lock} elsewhere "
                    f"in {cls.name})",
                    f"{cls.name}.{name}",
                )


class _MethodScan:
    """One pass over a method body tracking the held-lock set."""

    def __init__(self, engine: LockEngine, cls_name: str,
                 method, lock_attrs: set[str]):
        self.e = engine
        self.cls_name = cls_name
        self.method = method
        self.lock_attrs = lock_attrs
        self.accesses: list[_Access] = []
        self.calls: list[_CallSite] = []
        self._next_stmt: dict[int, ast.stmt] = {}
        self._acquire_stmts: dict[int, ast.stmt] = {}

    def run(self) -> None:
        self._walk(self.method.body, frozenset(), in_finally=False)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _walk_pruned(node: ast.AST):
        """ast.walk that does not descend into nested defs/lambdas —
        their bodies run in another execution context, so the enclosing
        lock state proves nothing about them."""
        stack = list(ast.iter_child_nodes(node))
        yield node
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _record_expr(self, node: ast.AST, held: frozenset,
                     in_finally: bool) -> None:
        """Record attribute accesses + call sites inside an expression."""
        nodes = list(self._walk_pruned(node))
        # bases of mutator calls / subscript stores are writes, not reads
        written_bases: set[int] = set()
        for n in nodes:
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in MUTATOR_METHODS \
                    and _self_attr(n.func.value) is not None:
                written_bases.add(id(n.func.value))
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.ctx, (ast.Store, ast.Del)) \
                    and _self_attr(n.value) is not None:
                written_bases.add(id(n.value))
        for n in nodes:
            if isinstance(n, ast.Call):
                # self.method(...) call site
                attr = _self_attr(n.func)
                if attr is not None:
                    self.calls.append(_CallSite(attr, held))
                if isinstance(n.func, ast.Attribute):
                    base_attr = _self_attr(n.func.value)
                    # mutator method on self.X -> write access
                    if base_attr is not None \
                            and n.func.attr in MUTATOR_METHODS:
                        self.accesses.append(
                            _Access(base_attr, True, n, held))
                    # PIO203: manual acquire on a lock attr
                    if base_attr in self.lock_attrs \
                            and n.func.attr == "acquire" \
                            and not in_finally:
                        self._check_acquire(n, base_attr, held)
            if isinstance(n, ast.Attribute):
                attr = _self_attr(n)
                if attr is None or id(n) in written_bases:
                    continue
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    self.accesses.append(_Access(attr, True, n, held))
                elif isinstance(n.ctx, ast.Load):
                    self.accesses.append(_Access(attr, False, n, held))
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(n.value)
                if attr is not None:
                    self.accesses.append(_Access(attr, True, n, held))

    def _check_acquire(self, call: ast.Call, lock_attr: str,
                       held: frozenset) -> None:
        """Flag ``self.X.acquire()`` unless the next statement is a
        try whose finally releases it."""
        stmt = self._acquire_stmts.get(id(call))
        ok = False
        if stmt is not None:
            nxt = self._next_stmt.get(id(stmt))
            if isinstance(nxt, ast.Try):
                for fin in nxt.finalbody:
                    for n in ast.walk(fin):
                        if isinstance(n, ast.Call) \
                                and isinstance(n.func, ast.Attribute) \
                                and n.func.attr == "release" \
                                and _self_attr(n.func.value) == lock_attr:
                            ok = True
        if not ok:
            self.e._emit(
                "PIO203", call,
                f"manual self.{lock_attr}.acquire() without an immediate "
                "try/finally release — an exception in between leaks the "
                "lock forever (use `with self." + lock_attr + ":`)",
                f"{self.cls_name}.{self.method.name}",
            )

    # -- statement walk ----------------------------------------------------
    def _walk(self, body: list, held: frozenset, in_finally: bool) -> None:
        # map each acquire-call expression statement to its next sibling
        # so _check_acquire can see the try/finally idiom
        for i, stmt in enumerate(body):
            if i + 1 < len(body):
                self._next_stmt[id(stmt)] = body[i + 1]
            if isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call):
                self._acquire_stmts[id(stmt.value)] = stmt
        for stmt in body:
            self._walk_stmt(stmt, held, in_finally)

    def _walk_stmt(self, stmt: ast.stmt, held: frozenset,
                   in_finally: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # other execution context; lock state doesn't carry
        if isinstance(stmt, ast.With):
            new_held = set(held)
            for item in stmt.items:
                attr = _self_attr(item.context_expr)
                if attr in self.lock_attrs:
                    new_held.add(attr)
                else:
                    self._record_expr(item.context_expr, held, in_finally)
            self._walk(stmt.body, frozenset(new_held), in_finally)
            return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, held, in_finally)
            for h in stmt.handlers:
                self._walk(h.body, held, in_finally)
            self._walk(stmt.orelse, held, in_finally)
            self._walk(stmt.finalbody, held, in_finally=True)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._record_expr(stmt.test, held, in_finally)
            self._walk(stmt.body, held, in_finally)
            self._walk(stmt.orelse, held, in_finally)
            return
        if isinstance(stmt, ast.For):
            self._record_expr(stmt.iter, held, in_finally)
            self._record_expr(stmt.target, held, in_finally)
            self._walk(stmt.body, held, in_finally)
            self._walk(stmt.orelse, held, in_finally)
            return
        # leaf statements: scan all contained expressions, but do not
        # descend into nested defs (handled above at statement level;
        # expressions can still contain lambdas — ignore their bodies)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            self._record_expr(child, held, in_finally)
