"""piolint engine-isolation rule (PIO301): engine files must not import
server internals.

The pio-forge contract is that an engine is ONE file declaring
DataSource/Algorithm(s)/Serving + params, and the PLATFORM supplies the
serving machinery (HTTP edges, micro-batcher, routers, tenancy).  An
engine module reaching into ``predictionio_tpu.server`` couples the
cheap-to-write layer to the hardest-to-change one: server internals are
refactored per-PR (threads -> eventloop, blocking -> continuous
batching), and an engine calling them directly would break on every
such change AND sidestep the obs/resilience wiring the platform routes
every query through.  Engines talk to the platform through the
``controller`` contracts and the shared ``templates/_common.py``
helpers (which may themselves wrap server utilities — infrastructure,
underscore-prefixed, outside this rule's scope).

Detection: any ``import``/``from ... import`` that resolves into the
``server`` package — absolute (``predictionio_tpu.server[.x]``) or
relative (``from ..server import ...`` / ``from ..server.microbatch
import ...``) — anywhere in an engine module, function-level imports
included (deferring the import defers the coupling, it doesn't remove
it).  The driver applies this engine only to engine modules:
``predictionio_tpu/templates/*.py`` excluding ``_``-prefixed
infrastructure files.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

__all__ = ["EngineImportEngine"]


def _is_server_module(dotted: str) -> bool:
    parts = dotted.split(".")
    if parts[:2] == ["predictionio_tpu", "server"]:
        return True
    # relative form: the module text after the dots ("server",
    # "server.microbatch") — the caller passes it with level noted
    return parts[0] == "server"


class EngineImportEngine:
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        f = self.src.finding(
            "PIO301", node,
            f"engine file imports server internals ({what}); "
            "engines declare components — the platform owns the "
            "serving machinery (use controller/_common APIs)",
        )
        if f is not None:
            self.findings.append(f)

    def run(self) -> list[Finding]:
        for node in self.src.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if _is_server_module(a.name):
                        self._flag(node, a.name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level == 0:
                    if _is_server_module(mod):
                        self._flag(node, mod)
                else:
                    # relative: `from ..server[...] import x` or
                    # `from .. import server`
                    if mod.split(".")[0] == "server":
                        self._flag(node, f"{'.' * node.level}{mod}")
                    elif not mod:
                        for a in node.names:
                            if a.name == "server":
                                self._flag(
                                    node, f"{'.' * node.level}server"
                                )
        return self.findings
