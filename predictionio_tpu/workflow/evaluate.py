"""Evaluation workflow driver.

`CoreWorkflow.runEvaluation` semantics
(`/root/reference/core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:96-150`
+ `EvaluationWorkflow.scala:29-42`): insert an EvaluationInstance, run the
sweep, record one-liner/HTML/JSON renderings for the dashboard, mark
EVALCOMPLETED.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from ..controller.base import WorkflowContext
from ..controller.engine import Engine, EngineParams
from ..controller.evaluation import Evaluation, MetricEvaluatorResult
from ..controller.fast_eval import FastEvalEngine
from ..obs import phase_span
from ..storage.event import format_time, now_utc
from ..storage.metadata import EvaluationInstance
from .params import WorkflowParams
from .train import new_instance_id

logger = logging.getLogger(__name__)

__all__ = ["run_evaluation"]


def run_evaluation(
    evaluation: Evaluation,
    engine_params_list: Optional[Sequence[EngineParams]] = None,
    ctx: Optional[WorkflowContext] = None,
    workflow_params: Optional[WorkflowParams] = None,
    evaluation_class: str = "",
    engine_params_generator_class: str = "",
    fast_eval: bool = True,
    parallelism: int = 1,
) -> tuple[str, MetricEvaluatorResult]:
    """Run the sweep; returns (evaluation instance id, result).

    ``parallelism > 1`` scores candidates from a thread pool and implies
    ``fast_eval=False`` (FastEval's prefix cache dedupes shared pipeline
    stages only for in-order candidates — running both would re-compute
    the prefixes it exists to save)."""
    if parallelism > 1:
        fast_eval = False
    ctx = ctx or WorkflowContext(mode="Evaluation")
    wp = workflow_params or WorkflowParams()
    md = ctx.storage.get_metadata()

    if engine_params_list is None:
        # resolve BEFORE inserting the instance record so a missing candidate
        # list fails cleanly instead of leaving a stuck INIT record
        candidates = getattr(evaluation, "engine_params_list", None)
        if candidates is None:
            raise ValueError(
                "no engine params candidates: pass engine_params_list, set "
                ".engine_params_list on the Evaluation, or supply an "
                "EngineParamsGenerator"
            )
        engine_params_list = list(candidates)

    eval_id = new_instance_id()
    rec = EvaluationInstance(
        id=eval_id,
        status="INIT",
        start_time=format_time(now_utc()),
        end_time="",
        evaluation_class=evaluation_class or type(evaluation).__name__,
        engine_params_generator_class=engine_params_generator_class,
        batch=wp.batch,
    )
    md.evaluation_instance_insert(rec)

    try:
        rec.status = "EVALUATING"
        md.evaluation_instance_update(rec)
        engine = evaluation.engine
        if parallelism > 1 and isinstance(engine, FastEvalEngine):
            # FastEval's check-then-insert prefix caches are not
            # thread-safe; a pre-wrapped engine must be unwrapped, not
            # just the auto-wrap skipped
            engine = Engine(
                engine.data_source_class_map,
                engine.preparator_class_map,
                engine.algorithm_class_map,
                engine.serving_class_map,
            )
            evaluation = Evaluation(
                engine, evaluation.metric, evaluation.metrics,
                evaluation.output_path,
            )
        elif fast_eval and not isinstance(engine, FastEvalEngine):
            engine = FastEvalEngine(engine)
            evaluation = Evaluation(
                engine, evaluation.metric, evaluation.metrics,
                evaluation.output_path,
            )
        # pio-tower: an eval run gets a manifest too — one candidate
        # record per scored sweep (MetricEvaluator._score_one appends
        # them), so "which candidate ate the wall time" outlives the log
        from ..obs import tower

        session = tower.TowerSession(
            eval_id,
            kind="eval",
            meta={
                "evaluationClass": rec.evaluation_class,
                "candidates": len(engine_params_list),
                "batch": wp.batch,
            },
        ).start()
        try:
            with phase_span("eval.run", attrs={
                "instance": eval_id, "candidates": len(engine_params_list),
            }):
                result = evaluation.run(
                    ctx, engine_params_list, wp, parallelism=parallelism
                )
            session.finalize("completed")
        except BaseException as e:
            session.finalize_error(e)
            raise
        rec.status = "EVALCOMPLETED"
        rec.end_time = format_time(now_utc())
        rec.evaluator_results = result.to_one_liner()
        rec.evaluator_results_html = result.to_html()
        rec.evaluator_results_json = result.to_json()
        md.evaluation_instance_update(rec)
        return eval_id, result
    except Exception:
        rec.status = "EVALFAILED"
        rec.end_time = format_time(now_utc())
        md.evaluation_instance_update(rec)
        raise
