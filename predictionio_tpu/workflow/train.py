"""Train + deploy-preparation drivers.

`CoreWorkflow.runTrain` semantics
(`/root/reference/core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:42-94`)
without Spark: one Python process drives the TPU mesh.  Lifecycle parity:
insert EngineInstance (INIT) -> train -> persist models -> COMPLETED;
failures mark the record and re-raise.  ``prepare_deploy`` mirrors
`Engine.prepareDeploy` (`controller/Engine.scala:173-243`) including the
compat retrain path for non-persisted models.
"""

from __future__ import annotations

import json
import logging
import uuid
from typing import Any, Optional

from ..controller.base import TrainingInterrupted, WorkflowContext
from ..controller.engine import Engine, EngineParams
from ..controller.params import params_to_json
from ..obs import phase_span
from ..storage.event import format_time, now_utc
from ..storage.metadata import EngineInstance
from .model_io import NotPersisted, load_models, save_models
from .params import WorkflowParams

logger = logging.getLogger(__name__)

__all__ = ["run_train", "prepare_deploy", "new_instance_id"]


def new_instance_id() -> str:
    return uuid.uuid4().hex[:16]


def _await_chief_terminal_status(
    md, instance_id: str, timeout: float = 1800.0
) -> None:
    """Non-chief wait for the chief's terminal instance status via the
    shared metadata store (the coordination plane every multi-host
    deployment already shares — the role HBase/ES played for the
    reference).  Raises if the chief recorded a failure or never wrote a
    terminal row (chief died before/inside its chief-only writes)."""
    import time as _time

    deadline = _time.time() + timeout
    while True:
        rec = md.engine_instance_get(instance_id)
        status = rec.status if rec is not None else "MISSING"
        if status == "COMPLETED":
            return
        if status in ("FAILED", "INTERRUPTED"):
            raise RuntimeError(
                f"training {status.lower()} on the chief process "
                f"(instance {instance_id})"
            )
        if _time.time() > deadline:
            raise TimeoutError(
                f"chief process never recorded a terminal status for "
                f"instance {instance_id} (last seen: {status}) within "
                f"{timeout}s"
            )
        _time.sleep(0.05)


def _shared_instance_id() -> str:
    """One instance id for the whole (possibly multi-process) run: chief
    draws it, everyone else receives it via collective broadcast."""
    import jax

    iid = new_instance_id()
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        buf = np.frombuffer(iid.encode("ascii"), dtype=np.uint8)
        buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        iid = buf.tobytes().decode("ascii")
    return iid


def _params_json(engine_params: EngineParams) -> dict[str, str]:
    return {
        "data_source_params": json.dumps(
            {engine_params.data_source[0]: params_to_json(engine_params.data_source[1])}
        ),
        "preparator_params": json.dumps(
            {engine_params.preparator[0]: params_to_json(engine_params.preparator[1])}
        ),
        "algorithms_params": json.dumps(
            [{n: params_to_json(p)} for n, p in engine_params.algorithms]
        ),
        "serving_params": json.dumps(
            {engine_params.serving[0]: params_to_json(engine_params.serving[1])}
        ),
    }


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    ctx: Optional[WorkflowContext] = None,
    workflow_params: Optional[WorkflowParams] = None,
    engine_id: str = "default",
    engine_version: str = "1",
    engine_variant: str = "engine.json",
    engine_factory: str = "",
) -> str:
    """Run training end-to-end; returns the engine instance id.

    Multi-host: all processes run the same training program (SPMD — the
    collectives inside require it); one instance id is broadcast from the
    chief, and only the chief writes the instance/model metadata rows (the
    reference's single Spark driver owns those writes; here every process
    is a "driver", so writes are explicitly gated).
    """
    import os
    import time

    import jax

    from ..obs import get_tracer, tower, xray

    # compile/device observability for the whole training run: every
    # half-iteration compile books into pio_jit_compiles_total{fn} and
    # the device sampler keeps the memory gauges live while we train
    xray.install()
    xray.start_sampler()

    ctx = ctx or WorkflowContext(mode="Training")
    wp = workflow_params or WorkflowParams()
    md = ctx.storage.get_metadata()
    chief = jax.process_index() == 0
    if jax.process_count() > 1:
        # stamp worker identity into span journals (pio-tower: a
        # cluster run's journals merge and grep by worker)
        get_tracer().set_process_index(jax.process_index())

    instance_id = _shared_instance_id()
    # pio-tower run session: chief writes the persistent run manifest;
    # every worker publishes registry snapshots into the coordination
    # dir (PIO_TPU_COORD_DIR — the multihost harness's rendezvous dir)
    # and the chief merges them into its /metrics and the manifest
    from ..engines import engine_label_of

    session = tower.TowerSession(
        instance_id,
        kind="train",
        meta={
            "engineId": engine_id,
            # pio-forge: the registered spec name rides every train
            # manifest so runlog list/diff can group runs by engine
            "engine": engine_label_of(engine, fallback=engine_id),
            "engineVariant": engine_variant,
            "batch": wp.batch,
            "nDevices": ctx.n_devices,
        },
        worker=jax.process_index(),
        n_workers=jax.process_count(),
        coord_dir=os.environ.get("PIO_TPU_COORD_DIR"),
    ).start()
    ei = EngineInstance(
        id=instance_id,
        status="INIT",
        start_time=format_time(now_utc()),
        end_time="",
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=wp.batch,
        mesh_conf={"n_devices": ctx.n_devices},
        **_params_json(engine_params),
    )
    if chief:
        md.engine_instance_insert(ei)

    completed = False
    try:
        ei.status = "TRAINING"
        if chief:
            md.engine_instance_update(ei)
        # keep the trained instances: persistence hooks may rely on state
        # the algorithm built during train
        t_run = time.perf_counter()
        with phase_span("train.run", attrs={"instance": instance_id}):
            algos, models = engine.train_components(ctx, engine_params, wp)
        session.note_train_run(time.perf_counter() - t_run)
        if wp.save_model:
            names = [n for n, _ in engine_params.algorithms]
            with phase_span("train.save_models",
                            attrs={"instance": instance_id}):
                save_models(
                    ctx, instance_id, list(zip(names, algos, models))
                )
        ei.status = "COMPLETED"
        ei.end_time = format_time(now_utc())
        if chief:
            md.engine_instance_update(ei)
        completed = True
        session.finalize("completed")
        logger.info("training finished: instance %s", instance_id)
        return instance_id
    except TrainingInterrupted as e:
        ei.status = "INTERRUPTED"
        ei.end_time = format_time(now_utc())
        if chief:
            md.engine_instance_update(ei)
        session.finalize("interrupted", error=str(e))
        raise
    except Exception as e:
        ei.status = "FAILED"
        ei.end_time = format_time(now_utc())
        if chief:
            md.engine_instance_update(ei)
        # a ConvergenceError was already finalized as "aborted" by the
        # watchdog (finalize is idempotent); anything else is "failed"
        session.finalize_error(e)
        raise
    finally:
        if jax.process_count() > 1 and not chief and completed:
            # Outcome agreement rides the SHARED METADATA STORE, not a
            # collective: a collective here could pair out of order with
            # one inside a failing peer's training step and hang.  The
            # chief's terminal status row is the verdict — non-chiefs
            # that finished their SPMD part wait for it (it also orders
            # the chief's COMPLETED row and model files before any
            # process returns or deploys).  Failures INSIDE the SPMD
            # phase are symmetric (every process raises) and skip this;
            # a chief that dies without writing any terminal status is
            # caught by the timeout.
            _await_chief_terminal_status(
                md, instance_id, timeout=wp.chief_wait_timeout_s
            )


def prepare_deploy(
    engine: Engine,
    engine_params: EngineParams,
    instance_id: str,
    ctx: Optional[WorkflowContext] = None,
) -> list[Any]:
    """Load persisted models for serving; retrain any NotPersisted model
    (reference `Engine.prepareDeploy` / `:186-208`)."""
    _, models, _ = prepare_deploy_components(
        engine, engine_params, instance_id, ctx
    )
    return models


def prepare_deploy_components(
    engine: Engine,
    engine_params: EngineParams,
    instance_id: str,
    ctx: Optional[WorkflowContext] = None,
) -> tuple[list[Any], list[Any], Any]:
    """Like :func:`prepare_deploy`, but returns the serving-ready component
    instances too: ``(algorithms, models, serving)``.  Algorithms get the
    serving context attached (``_ctx``) so predict-time event-store reads
    (e.g. the ecommerce template) resolve the same storage the deployment
    uses — the reference reaches this via the Storage global."""
    ctx = ctx or WorkflowContext(mode="Serving")
    algos = engine._algorithms(engine_params)
    for a in algos:
        a._ctx = ctx
    names = [n for n, _ in engine_params.algorithms]
    models = load_models(ctx, instance_id, list(zip(names, algos)))
    missing = [i for i, m in enumerate(models) if isinstance(m, NotPersisted)]
    if missing:
        logger.warning(
            "models %s of instance %s were not persisted; retraining those",
            missing, instance_id,
        )
        _, retrained = engine.train_components(
            ctx, engine_params, WorkflowParams(save_model=False),
            algo_indices=missing,
        )
        for i, model in zip(missing, retrained):
            models[i] = model
    serving = engine._serving(engine_params)
    return algos, models, serving
