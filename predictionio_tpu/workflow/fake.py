"""Ad-hoc workflow runner (the reference's FakeWorkflow).

Parity with `core/src/main/scala/io/prediction/workflow/FakeWorkflow.scala:16-91`:
``FakeRun`` lets an arbitrary ``WorkflowContext -> None`` function execute
under the full framework environment — storage resolved, an
EvaluationInstance recorded with lifecycle status — exactly as if it were a
real evaluation.  Used for experiments and smoke scripts (``pio eval
SomeFakeRunObj`` in the reference; ``run_fake(fn)`` here).
"""

from __future__ import annotations

import logging
import traceback
from typing import Callable, Optional

from ..controller.base import WorkflowContext
from ..storage.event import format_time, now_utc
from ..storage.metadata import EvaluationInstance
from .train import new_instance_id

logger = logging.getLogger(__name__)

__all__ = ["FakeRun", "run_fake"]


class FakeRun:
    """Wraps a context function so workflow tooling can run it like an
    evaluation (reference ``FakeRun`` / ``FakeEvaluator``)."""

    def __init__(self, func: Callable[[WorkflowContext], None]):
        self.func = func

    def run(self, ctx: Optional[WorkflowContext] = None) -> str:
        return run_fake(self.func, ctx)


def run_fake(
    func: Callable[[WorkflowContext], None],
    ctx: Optional[WorkflowContext] = None,
) -> str:
    """Execute ``func(ctx)`` under a recorded evaluation instance; returns
    the instance id."""
    ctx = ctx or WorkflowContext(mode="Evaluation")
    md = ctx.storage.get_metadata()
    eval_id = new_instance_id()
    rec = EvaluationInstance(
        id=eval_id,
        status="INIT",
        start_time=format_time(now_utc()),
        end_time="",
        evaluation_class=getattr(func, "__qualname__", repr(func)),
        engine_params_generator_class="",
        batch="FakeRun",
    )
    md.evaluation_instance_insert(rec)
    try:
        rec.status = "EVALUATING"
        md.evaluation_instance_update(rec)
        func(ctx)
        rec.status = "EVALCOMPLETED"
        rec.evaluator_results = "FakeRun completed"
    except Exception:
        rec.status = "EVALFAILED"
        rec.evaluator_results = traceback.format_exc(limit=5)
        raise
    finally:
        rec.end_time = format_time(now_utc())
        md.evaluation_instance_update(rec)
    return eval_id
