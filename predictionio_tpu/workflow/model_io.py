"""Model persistence: sharded-array checkpoints + pickled host models.

Replaces the reference's Kryo-blob path (`workflow/CoreWorkflow.scala:69-74`,
`storage/Models.scala:30-48`) and the `PersistentModel` contract
(`controller/PersistentModel.scala:48-95`).  Policy (SURVEY §7 hard-part 6):

* every model is persisted by default (the reference's silent
  PAlgorithm-retrain-at-deploy is kept only as a compat path for algorithms
  that set ``persist_model = False``);
* device models (pytrees of ``jax.Array``) are converted to NumPy host
  buffers and written as ``.npz`` + pickled structure — cheap, dependency
  -free, and reshardable on load (the loader re-places arrays onto the
  current mesh, which may differ from the training mesh);
* algorithms may override ``save_model``/``load_model`` for custom formats.

The metadata `models` table stores the manifest JSON keyed by
``<instance_id>-<algo_ix>-<algo_name>`` (same key scheme as the reference's
``makeSerializableModels``, `controller/Engine.scala:260-278`).
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Any, Optional

import numpy as np

from ..controller.base import Algorithm, WorkflowContext
from ..storage.metadata import Model

__all__ = ["save_models", "load_models", "NotPersisted"]


class NotPersisted:
    """Marker: model was not persisted; deploy must retrain
    (reference `controller/Engine.scala:186-208`)."""


def _to_host(tree: Any) -> Any:
    """jax.Array leaves -> numpy (identity for plain host models)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree
    )


def model_key(instance_id: str, ax: int, name: str) -> str:
    return "-".join([instance_id, str(ax), name])


def save_models(
    ctx: WorkflowContext,
    instance_id: str,
    algo_tuples: list[tuple[str, Algorithm, Any]],
) -> None:
    """Persist every algorithm's model; manifest goes into the models repo."""
    md = ctx.storage.get_metadata()
    base_dir = ctx.storage.model_data_dir() / instance_id
    for ax, (name, algo, model) in enumerate(algo_tuples):
        key = model_key(instance_id, ax, name)
        if not algo.persist_model:
            manifest = {"kind": "not_persisted"}
        else:
            custom = algo.save_model(ctx, key, model, base_dir)
            if custom is not None:
                manifest = {"kind": "custom", "custom": custom}
            else:
                base_dir.mkdir(parents=True, exist_ok=True)
                fname = f"model_{ax}_{name or 'default'}.pkl"
                with open(base_dir / fname, "wb") as f:
                    pickle.dump(_to_host(model), f, protocol=pickle.HIGHEST_PROTOCOL)
                # store the name relative to base_dir so the storage tree
                # can be relocated between train and deploy hosts
                manifest = {"kind": "pickle", "file": fname}
        md.model_insert(Model(id=key, models=json.dumps(manifest).encode()))


def load_models(
    ctx: WorkflowContext,
    instance_id: str,
    algo_tuples: list[tuple[str, Algorithm]],
) -> list[Any]:
    """Load (or mark-for-retrain) each algorithm's model for deployment."""
    md = ctx.storage.get_metadata()
    base_dir = ctx.storage.model_data_dir() / instance_id
    out: list[Any] = []
    for ax, (name, algo) in enumerate(algo_tuples):
        key = model_key(instance_id, ax, name)
        rec = md.model_get(key)
        if rec is None:
            out.append(NotPersisted())
            continue
        manifest = json.loads(rec.models.decode())
        kind = manifest.get("kind")
        if kind == "not_persisted":
            out.append(NotPersisted())
        elif kind == "custom":
            out.append(algo.load_model(ctx, key, manifest["custom"], base_dir))
        elif kind == "pickle":
            path = (
                base_dir / manifest["file"]
                if "file" in manifest
                else Path(manifest["path"])
            )
            with open(path, "rb") as f:
                out.append(pickle.load(f))
        else:
            raise ValueError(f"unknown model manifest kind: {kind!r}")
    return out
