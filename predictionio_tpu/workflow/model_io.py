"""Model persistence: sharded-array checkpoints + pickled host models.

Replaces the reference's Kryo-blob path (`workflow/CoreWorkflow.scala:69-74`,
`storage/Models.scala:30-48`) and the `PersistentModel` contract
(`controller/PersistentModel.scala:48-95`).  Policy (SURVEY §7 hard-part 6):

* every model is persisted by default (the reference's silent
  PAlgorithm-retrain-at-deploy is kept only as a compat path for algorithms
  that set ``persist_model = False``);
* device models (pytrees of ``jax.Array``) are converted to NumPy host
  buffers and written as ``.npz`` + pickled structure — cheap, dependency
  -free, and reshardable on load (the loader re-places arrays onto the
  current mesh, which may differ from the training mesh);
* algorithms may override ``save_model``/``load_model`` for custom formats.

The metadata `models` table stores the manifest JSON keyed by
``<instance_id>-<algo_ix>-<algo_name>`` (same key scheme as the reference's
``makeSerializableModels``, `controller/Engine.scala:260-278`).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pickle
import re
from pathlib import Path
from typing import Any, Optional

import numpy as np

from ..controller.base import Algorithm, ModelPlacement, WorkflowContext
from ..storage.metadata import Model

__all__ = [
    "save_models",
    "load_models",
    "NotPersisted",
    "ModelDelta",
    "DELTA_VERSION",
    "delta_file_name",
    "save_model_delta",
    "load_model_delta",
    "list_model_deltas",
    "load_model_delta_chain",
]

logger = logging.getLogger(__name__)


class NotPersisted:
    """Marker: model was not persisted; deploy must retrain
    (reference `controller/Engine.scala:186-208`)."""


def _fetch_global(v: Any) -> np.ndarray:
    """Numpy value of a possibly process-sharded array.

    ``np.asarray`` raises on a ``jax.Array`` that spans non-addressable
    devices (multi-host training with sharded factor tables); those are
    fully replicated with ``process_allgather`` first.  For such arrays
    this is a COLLECTIVE — every process must reach it in the same order,
    which is why save runs the conversions on all processes and gates only
    the file writes on the chief.
    """
    import jax

    if isinstance(v, jax.Array) and not (
        v.is_fully_addressable or v.is_fully_replicated
    ):
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(v))
    return np.asarray(v)


def _to_host(tree: Any) -> Any:
    """jax.Array leaves -> numpy (identity for plain host models)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: _fetch_global(x) if isinstance(x, jax.Array) else x, tree
    )


# --------------------------------------------------------------------------
# DEVICE_SHARDED persistence: array fields as .npz with recorded partition
# specs, re-placed onto the CURRENT mesh at load (which may be a different
# size than the training mesh) — the load-bearing consequence of the
# reference's P/P2L/L taxonomy (`controller/PAlgorithm.scala:45-121`:
# distributed models need an explicit persistence format; local models
# serialize as blobs).
# --------------------------------------------------------------------------


def _split_array_fields(model: Any):
    """Dataclass model -> ({array fields}, {other fields}), or None if the
    model can't round-trip through ``cls(**fields)`` (not a dataclass, or
    it has init=False fields whose state would be silently dropped) —
    caller falls back to pickle."""
    if not dataclasses.is_dataclass(model) or isinstance(model, type):
        return None
    if any(not f.init for f in dataclasses.fields(model)):
        return None
    import jax

    arrays: dict[str, Any] = {}
    rest: dict[str, Any] = {}
    for f in dataclasses.fields(model):
        v = getattr(model, f.name)
        # only numeric/bool arrays ride the npz (object-dtype arrays would
        # save fine but be unloadable under allow_pickle=False)
        if (
            isinstance(v, (np.ndarray, jax.Array))
            and getattr(v, "ndim", 0) >= 1
            and np.dtype(v.dtype).kind in "biufc"
        ):
            arrays[f.name] = v
        else:
            rest[f.name] = v
    return arrays, rest


def _spec_of(v: Any) -> Optional[list]:
    """JSON-able partition spec of a sharded jax.Array, else None."""
    import jax
    from jax.sharding import NamedSharding

    if not isinstance(v, jax.Array):
        return None
    sh = v.sharding
    if not isinstance(sh, NamedSharding) or sh.is_fully_replicated:
        return None
    out = []
    for entry in sh.spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(e) for e in entry])
        else:
            out.append(str(entry))
    return out


def _save_sharded(
    model: Any, base_dir: Path, key: str, chief: bool = True
) -> Optional[dict]:
    """DEVICE_SHARDED format: one .npz of array fields + pickled rest;
    per-field partition specs go in the manifest.  Returns None when the
    model has no recognizable array fields (caller falls back to pickle).

    Device->host conversions run on EVERY process (they are collectives
    for process-sharded arrays); only the chief writes the files.
    """
    split = _split_array_fields(model)
    if split is None or not split[0]:
        return None
    arrays, rest = split
    npz_name = f"{key}-arrays.npz"
    rest_name = f"{key}-rest.pkl"
    host_arrays = {k: _fetch_global(v) for k, v in arrays.items()}
    # _to_host: jax scalars / arrays nested inside non-array fields
    # (dicts, lists, 0-d values) must land as numpy, same as the pickle
    # blob path — a device-backed value here would fail to pickle or
    # pin device state
    host_rest = _to_host(rest)
    if chief:
        base_dir.mkdir(parents=True, exist_ok=True)
        np.savez_compressed(base_dir / npz_name, **host_arrays)
        with open(base_dir / rest_name, "wb") as f:
            pickle.dump({"cls": type(model), "fields": host_rest}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
    return {
        "kind": "sharded",
        "npz": npz_name,
        "rest": rest_name,
        "specs": {k: _spec_of(v) for k, v in arrays.items()},
    }


def _load_sharded(
    ctx: WorkflowContext, manifest: dict, base_dir: Path
) -> Any:
    """Rebuild a DEVICE_SHARDED model, re-placing each recorded-spec array
    onto the CURRENT mesh (any size whose axis names match)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    with open(base_dir / manifest["rest"], "rb") as f:
        rest = pickle.load(f)
    data = np.load(base_dir / manifest["npz"], allow_pickle=False)
    mesh = getattr(ctx, "mesh", None)
    kw = dict(rest["fields"])
    for k in data.files:
        arr = data[k]
        spec = manifest.get("specs", {}).get(k)
        if spec is not None and mesh is not None and mesh.size > 1:
            names = {
                n
                for e in spec
                if e is not None
                for n in (e if isinstance(e, list) else [e])
            }
            if names <= set(mesh.axis_names):
                entries = [
                    tuple(e) if isinstance(e, list) else e for e in spec
                ]
                arr = jax.device_put(
                    arr, NamedSharding(mesh, PartitionSpec(*entries))
                )
            else:
                logger.warning(
                    "model array %r recorded axes %s not in serving mesh "
                    "%s; loading replicated", k, names, mesh.axis_names,
                )
        kw[k] = arr
    return rest["cls"](**kw)


def model_key(instance_id: str, ax: int, name: str) -> str:
    return "-".join([instance_id, str(ax), name])


# --------------------------------------------------------------------------
# Delta model format (pio-live): a versioned chain of row-level patches
# against the last FULL checkpoint of a factor model.  Each delta is one
# atomically-written .npz holding patched factor rows, appended rows with
# their new entity ids, and a JSON meta blob carrying the chain links
# (seq, prev seq, instance, watermark).  The serving layer applies deltas
# in sequence without a stop-the-world reload; a torn or missing link
# truncates the chain at the last good delta — falling back toward the
# full model, never past it (the same contract as StepCheckpointer's
# torn-newest-step fallback).
# --------------------------------------------------------------------------

DELTA_VERSION = 1

_DELTA_RE = re.compile(r"-delta-(\d{8})\.npz$")


@dataclasses.dataclass
class ModelDelta:
    """One link of a delta chain.

    Row indices address the table AS OF the previous link (the full
    model for seq 1): appended rows land at ``base_n_*`` onward, so a
    chain is only applicable in contiguous seq order.
    """

    seq: int
    meta: dict
    user_rows_ix: np.ndarray   # int32 [p] rows patched in the user table
    user_rows: np.ndarray      # f32 [p, R]
    new_user_ids: np.ndarray   # unicode [a] appended user ids
    new_user_rows: np.ndarray  # f32 [a, R]
    item_rows_ix: np.ndarray   # int32 [q] rows patched in the item table
    item_rows: np.ndarray      # f32 [q, R]
    new_item_ids: np.ndarray   # unicode [b] appended item ids
    new_item_rows: np.ndarray  # f32 [b, R]

    @property
    def watermark(self) -> Optional[dict]:
        return self.meta.get("watermark")

    def counts(self) -> dict:
        return {
            "patchedUsers": int(len(self.user_rows_ix)),
            "appendedUsers": int(len(self.new_user_ids)),
            "patchedItems": int(len(self.item_rows_ix)),
            "appendedItems": int(len(self.new_item_ids)),
        }


def delta_file_name(key: str, seq: int) -> str:
    return f"{key}-delta-{seq:08d}.npz"


def save_model_delta(
    base_dir: Path, key: str, delta: ModelDelta
) -> Path:
    """Write one delta link atomically (tmp + rename): a reader either
    sees the previous chain or the complete new link, never a torn
    file — a crash mid-write leaves only a ``.tmp`` orphan that the
    chain loader ignores."""
    base_dir = Path(base_dir)
    base_dir.mkdir(parents=True, exist_ok=True)
    meta = dict(delta.meta)
    meta.setdefault("version", DELTA_VERSION)
    meta["seq"] = int(delta.seq)
    path = base_dir / delta_file_name(key, delta.seq)
    tmp = path.with_suffix(".npz.tmp")
    rank_arrays = {
        "user_rows_ix": np.asarray(delta.user_rows_ix, np.int32),
        "user_rows": np.asarray(delta.user_rows, np.float32),
        "new_user_rows": np.asarray(delta.new_user_rows, np.float32),
        "item_rows_ix": np.asarray(delta.item_rows_ix, np.int32),
        "item_rows": np.asarray(delta.item_rows, np.float32),
        "new_item_rows": np.asarray(delta.new_item_rows, np.float32),
        # unicode ('U') arrays round-trip under allow_pickle=False;
        # object arrays would not
        "new_user_ids": np.asarray(
            [str(s) for s in delta.new_user_ids], dtype=np.str_
        ),
        "new_item_ids": np.asarray(
            [str(s) for s in delta.new_item_ids], dtype=np.str_
        ),
        "meta_json": np.asarray(
            json.dumps(meta, separators=(",", ":"))
        ),
    }
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **rank_arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_model_delta(path: Path) -> ModelDelta:
    """Load one delta link; raises on a torn/truncated/foreign file
    (the chain loader turns that into a clean truncation)."""
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["meta_json"]))
        if int(meta.get("version", -1)) > DELTA_VERSION:
            raise ValueError(
                f"delta {path.name} has version {meta.get('version')}, "
                f"newer than this framework's {DELTA_VERSION}"
            )
        return ModelDelta(
            seq=int(meta["seq"]),
            meta=meta,
            user_rows_ix=data["user_rows_ix"],
            user_rows=data["user_rows"],
            new_user_ids=data["new_user_ids"],
            new_user_rows=data["new_user_rows"],
            item_rows_ix=data["item_rows_ix"],
            item_rows=data["item_rows"],
            new_item_ids=data["new_item_ids"],
            new_item_rows=data["new_item_rows"],
        )


def list_model_deltas(base_dir: Path, key: str) -> list[tuple[int, Path]]:
    """(seq, path) pairs of the on-disk chain for ``key``, seq-sorted.
    ``.tmp`` orphans from crashed writes never match."""
    base_dir = Path(base_dir)
    if not base_dir.is_dir():
        return []
    out = []
    prefix = f"{key}-delta-"
    for p in base_dir.iterdir():
        if not p.name.startswith(prefix):
            continue
        m = _DELTA_RE.search(p.name)
        if m:
            out.append((int(m.group(1)), p))
    out.sort()
    return out


def load_model_delta_chain(
    base_dir: Path, key: str, after_seq: int = 0
) -> tuple[list["ModelDelta"], Optional[str]]:
    """Load the applicable chain suffix: every delta with ``seq >
    after_seq``, in order, stopping at the first gap or unreadable
    link.

    Returns ``(deltas, error)``.  ``error`` is None for a clean chain;
    otherwise a human-readable reason for the truncation.  A truncated
    chain is NOT a failure mode for the caller — applying the good
    prefix (possibly empty) falls back toward the last full model,
    which is the stale-model-beats-no-model contract serving already
    has for failed reloads.  Appended-row indices make out-of-order or
    gapped application corrupting, so a gap truncates just like a torn
    file.
    """
    out: list[ModelDelta] = []
    err: Optional[str] = None
    expect = int(after_seq) + 1
    for seq, path in list_model_deltas(base_dir, key):
        if seq <= after_seq:
            continue
        if seq != expect:
            err = (
                f"delta chain gap: expected seq {expect}, found "
                f"{path.name}; applying only the contiguous prefix"
            )
            break
        try:
            out.append(load_model_delta(path))
        except Exception as e:
            err = (
                f"delta {path.name} is unreadable "
                f"({type(e).__name__}: {e}); falling back to the "
                f"chain before it"
            )
            break
        expect += 1
    return out, err


def save_models(
    ctx: WorkflowContext,
    instance_id: str,
    algo_tuples: list[tuple[str, Algorithm, Any]],
) -> None:
    """Persist every algorithm's model; manifest goes into the models repo.

    Multi-host: every process runs the device->host conversions (collectives
    for process-sharded arrays) and custom ``save_model`` hooks (which must
    gate their own file IO on ``jax.process_index() == 0`` if they write);
    only process 0 writes files and metadata rows.  Callers outside
    ``run_train`` that need "files visible on every host before use" must
    order that through the shared metadata store the way ``run_train``
    does (wait for the chief's terminal instance status), not a barrier.
    """
    import jax

    chief = jax.process_index() == 0
    md = ctx.storage.get_metadata()
    base_dir = ctx.storage.model_data_dir() / instance_id
    # NO collective barrier here: a barrier could pair out of order with a
    # collective inside a failing peer and hang.  "Files exist before any
    # process deploys" is guaranteed through the shared metadata store
    # instead — run_train's non-chief processes wait for the chief's
    # terminal status row, which the chief writes only after this returns.
    for ax, (name, algo, model) in enumerate(algo_tuples):
        key = model_key(instance_id, ax, name)
        if not algo.persist_model:
            manifest = {"kind": "not_persisted"}
        else:
            custom = algo.save_model(ctx, key, model, base_dir)
            if custom is not None:
                manifest = {"kind": "custom", "custom": custom}
            else:
                manifest = None
                if algo.placement is ModelPlacement.DEVICE_SHARDED:
                    # placement drives the persistence format: sharded
                    # models round-trip as array files + partition specs
                    # so deploy can re-place them on a different mesh
                    manifest = _save_sharded(model, base_dir, key,
                                             chief=chief)
                if manifest is None:
                    payload = _to_host(model)  # collective: all processes
                    fname = f"model_{ax}_{name or 'default'}.pkl"
                    if chief:
                        base_dir.mkdir(parents=True, exist_ok=True)
                        with open(base_dir / fname, "wb") as f:
                            pickle.dump(payload, f,
                                        protocol=pickle.HIGHEST_PROTOCOL)
                    # store the name relative to base_dir so the storage
                    # tree can be relocated between train and deploy hosts
                    manifest = {"kind": "pickle", "file": fname}
        if chief:
            md.model_insert(
                Model(id=key, models=json.dumps(manifest).encode())
            )


def load_models(
    ctx: WorkflowContext,
    instance_id: str,
    algo_tuples: list[tuple[str, Algorithm]],
) -> list[Any]:
    """Load (or mark-for-retrain) each algorithm's model for deployment."""
    md = ctx.storage.get_metadata()
    base_dir = ctx.storage.model_data_dir() / instance_id
    out: list[Any] = []
    for ax, (name, algo) in enumerate(algo_tuples):
        key = model_key(instance_id, ax, name)
        rec = md.model_get(key)
        if rec is None:
            out.append(NotPersisted())
            continue
        manifest = json.loads(rec.models.decode())
        kind = manifest.get("kind")
        if kind == "not_persisted":
            out.append(NotPersisted())
        elif kind == "custom":
            out.append(algo.load_model(ctx, key, manifest["custom"], base_dir))
        elif kind == "sharded":
            out.append(_load_sharded(ctx, manifest, base_dir))
        elif kind == "pickle":
            path = (
                base_dir / manifest["file"]
                if "file" in manifest
                else Path(manifest["path"])
            )
            with open(path, "rb") as f:
                out.append(pickle.load(f))
        else:
            raise ValueError(f"unknown model manifest kind: {kind!r}")
    return out
