"""Workflow orchestration: train/eval/deploy drivers
(reference `/root/reference/core/src/main/scala/io/prediction/workflow/`)."""

from .model_io import NotPersisted, load_models, save_models
from .params import WorkflowParams
from .evaluate import run_evaluation
from .train import new_instance_id, prepare_deploy, run_train

__all__ = [
    "NotPersisted",
    "load_models",
    "save_models",
    "WorkflowParams",
    "new_instance_id",
    "prepare_deploy",
    "run_train",
    "run_evaluation",
]
