"""Workflow orchestration: train/eval/deploy drivers
(reference `/root/reference/core/src/main/scala/io/prediction/workflow/`)."""

from .model_io import NotPersisted, load_models, save_models
from .params import WorkflowParams
from .evaluate import run_evaluation
from .fake import FakeRun, run_fake
from .train import new_instance_id, prepare_deploy, run_train

__all__ = [
    "FakeRun",
    "run_fake",
    "NotPersisted",
    "load_models",
    "save_models",
    "WorkflowParams",
    "new_instance_id",
    "prepare_deploy",
    "run_train",
    "run_evaluation",
]
