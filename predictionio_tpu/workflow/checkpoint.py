"""Step checkpointing for long training runs (orbax-backed).

The reference has model persistence but **no step checkpointing** — a
failed Spark job reruns from scratch (SURVEY §5 "Checkpoint / resume").
Here long ALS runs can checkpoint factor state every K iterations and
resume deterministically; orbax writes sharded ``jax.Array`` pytrees so
every host of a multi-host mesh saves only its own shards.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Optional

logger = logging.getLogger(__name__)

__all__ = ["StepCheckpointer"]


class StepCheckpointer:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Saves arbitrary pytrees keyed by integer step; restores the latest
    (or a given) step, preserving shardings when restoring like-for-like
    on the same mesh.
    """

    def __init__(self, directory: str | Path, keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    def save(self, step: int, tree: Any, wait: bool = True) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()
        logger.info("checkpoint step %d -> %s", step, self.directory)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore ``step`` (default latest).  ``like`` — a pytree of
        arrays or ShapeDtypeStructs with target shardings — makes orbax
        place the restored shards directly onto the current mesh."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if like is not None:
            import jax

            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None)
                ),
                like,
            )
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(abstract)
            )
        return self._mgr.restore(step)

    def close(self) -> None:
        self._mgr.close()
