"""Step checkpointing for long training runs (orbax-backed).

The reference has model persistence but **no step checkpointing** — a
failed Spark job reruns from scratch (SURVEY §5 "Checkpoint / resume").
Here long ALS runs can checkpoint factor state every K iterations and
resume deterministically; orbax writes sharded ``jax.Array`` pytrees so
every host of a multi-host mesh saves only its own shards.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Optional

logger = logging.getLogger(__name__)

__all__ = ["StepCheckpointer"]


class StepCheckpointer:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Saves arbitrary pytrees keyed by integer step; restores the latest
    (or a given) step, preserving shardings when restoring like-for-like
    on the same mesh.
    """

    def __init__(self, directory: str | Path, keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )
        self.last_restored_step: Optional[int] = None

    def save(self, step: int, tree: Any, wait: bool = True) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()
        logger.info("checkpoint step %d -> %s", step, self.directory)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore ``step`` (default latest).  ``like`` — a pytree of
        arrays or ShapeDtypeStructs with target shardings — makes orbax
        place the restored shards directly onto the current mesh.

        When no explicit ``step`` was requested and the newest
        checkpoint turns out torn (a crash mid-write, a truncated
        object store upload), restore falls back through older steps
        instead of failing the whole resume — losing K iterations of
        progress beats losing the run.  An explicitly requested step
        never falls back: the caller asked for *that* state.
        The step actually restored is recorded as
        ``last_restored_step``."""
        explicit = step is not None
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        candidates = [step] if explicit else sorted(
            (s for s in self._mgr.all_steps() if s <= step), reverse=True
        ) or [step]
        last_err: Optional[BaseException] = None
        for i, s in enumerate(candidates):
            try:
                out = self._restore_step(s, like)
            except Exception as e:
                last_err = e
                if i + 1 < len(candidates):
                    logger.warning(
                        "checkpoint step %d is unreadable (%s: %s); "
                        "falling back to step %d",
                        s, type(e).__name__, e, candidates[i + 1],
                    )
                continue
            self.last_restored_step = s
            return out
        raise last_err

    def _restore_step(self, step: int, like: Any = None) -> Any:
        if like is not None:
            import jax

            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None)
                ),
                like,
            )
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(abstract)
            )
        return self._mgr.restore(step)

    def close(self) -> None:
        self._mgr.close()
