"""Workflow control parameters
(reference `/root/reference/core/src/main/scala/io/prediction/workflow/WorkflowParams.scala:29-42`)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
