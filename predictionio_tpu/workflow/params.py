"""Workflow control parameters
(reference `/root/reference/core/src/main/scala/io/prediction/workflow/WorkflowParams.scala:29-42`)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    # multi-host: how long non-chief processes wait for the chief's
    # terminal instance-status row after finishing their SPMD part — the
    # chief may still be writing a large model to shared storage.  Size
    # to the slowest expected model write, not the train itself.
    chief_wait_timeout_s: float = 1800.0
