"""Native (C++) host runtime, loaded via ctypes.

Holds the in-process equivalents of work the reference shipped to Spark
executors:

* O(n) counting-sort COO preprocessing for ALS (``native/bucketize.cpp``;
  reference analogue: the executor-side shuffle in MLlib ALS).
* bulk JSON-lines event scanning for the importer
  (``native/jsonl_scan.cpp``; reference analogue: the FileToEvents Spark
  job, `tools/.../imprt/FileToEvents.scala:30-95`).

The library is compiled on demand with the system toolchain and cached
under ``$PIO_TPU_HOME/native``; every entry point has a pure-Python/NumPy
fallback so the framework runs (slower) without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["sort_coo_by_row", "scan_events_jsonl", "scan_ratings_sqlite",
           "native_available"]


class _PioRatingsScan(ctypes.Structure):
    # mirrors PioRatingsScan in native/sqlite_scan.cpp
    _fields_ = [
        ("n", ctypes.c_int64),
        ("u_codes", ctypes.POINTER(ctypes.c_int32)),
        ("i_codes", ctypes.POINTER(ctypes.c_int32)),
        ("values", ctypes.POINTER(ctypes.c_double)),
        ("times", ctypes.POINTER(ctypes.c_int64)),
        ("n_users", ctypes.c_int64),
        ("n_items", ctypes.c_int64),
        ("user_arena", ctypes.POINTER(ctypes.c_char)),
        ("user_offs", ctypes.POINTER(ctypes.c_int64)),
        ("item_arena", ctypes.POINTER(ctypes.c_char)),
        ("item_offs", ctypes.POINTER(ctypes.c_int64)),
        ("err", ctypes.c_char * 256),
    ]

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SRCS = [_NATIVE_DIR / "bucketize.cpp", _NATIVE_DIR / "jsonl_scan.cpp",
         _NATIVE_DIR / "sqlite_scan.cpp"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> Path:
    home = os.environ.get("PIO_TPU_HOME") or os.path.expanduser(
        "~/.predictionio_tpu"
    )
    p = Path(home) / "native"
    p.mkdir(parents=True, exist_ok=True)
    return p


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        srcs = [p for p in _SRCS if p.exists()]
        if not srcs:
            logger.debug("native sources missing under %s; using NumPy path",
                         _NATIVE_DIR)
            return None
        so = _cache_dir() / "_native.so"
        try:
            newest = max(p.stat().st_mtime for p in srcs)
            if not so.exists() or so.stat().st_mtime < newest:
                # compile to a private temp name and publish atomically so
                # concurrent processes never dlopen a half-written file
                tmp = so.with_suffix(f".{os.getpid()}.tmp")
                try:
                    # -l:libsqlite3.so.0 — the image ships the runtime
                    # library but no dev symlink/header; the colon form
                    # links the exact soname (sqlite_scan.cpp declares
                    # the ABI-stable prototypes itself).  If THAT link
                    # fails (no libsqlite3 in the linker path, or a
                    # toolchain without -l: support), retry without the
                    # sqlite kernel so the other native kernels keep
                    # their acceleration instead of all regressing to
                    # NumPy.
                    base = ["g++", "-O3", "-shared", "-fPIC"]
                    try:
                        subprocess.run(
                            base + [str(p) for p in srcs]
                            + ["-o", str(tmp), "-l:libsqlite3.so.0"],
                            check=True, capture_output=True, timeout=120,
                        )
                    except subprocess.CalledProcessError as ce:
                        # keep the compiler's own words: a syntax error
                        # in any source would otherwise masquerade as a
                        # libsqlite3 linking problem
                        logger.warning(
                            "sqlite-linked native build failed "
                            "(stderr tail: %s); rebuilding without the "
                            "sqlite scan kernel",
                            (ce.stderr or b"")[-500:].decode(
                                "utf-8", "replace"
                            ),
                        )
                        subprocess.run(
                            base + [
                                str(p) for p in srcs
                                if p.name != "sqlite_scan.cpp"
                            ] + ["-o", str(tmp)],
                            check=True, capture_output=True, timeout=120,
                        )
                    os.replace(tmp, so)
                finally:
                    tmp.unlink(missing_ok=True)
            lib = ctypes.CDLL(str(so))
        except (OSError, subprocess.SubprocessError) as e:
            logger.warning("native build unavailable (%s); NumPy path", e)
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.pio_count_rows.argtypes = [i32p, ctypes.c_int64, i64p]
        lib.pio_count_rows.restype = None
        lib.pio_sort_coo.argtypes = [
            i32p, i32p, f32p, ctypes.c_int64, ctypes.c_int64,
            i64p, i64p, i32p, f32p,
        ]
        lib.pio_sort_coo.restype = None
        if hasattr(lib, "pio_scan_ratings_sql"):
            lib.pio_scan_ratings_sql.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                ctypes.c_int,
            ]
            lib.pio_scan_ratings_sql.restype = ctypes.POINTER(
                _PioRatingsScan
            )
            lib.pio_scan_ratings_free.argtypes = [
                ctypes.POINTER(_PioRatingsScan)
            ]
            lib.pio_scan_ratings_free.restype = None
        if hasattr(lib, "pio_scan_events_jsonl"):
            lib.pio_scan_events_jsonl.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                i64p, i32p, i64p, i64p, i64p, i32p, i32p,
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.pio_scan_events_jsonl.restype = ctypes.c_int64
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def sort_coo_by_row(
    row_ix: np.ndarray,
    col_ix: np.ndarray,
    val: np.ndarray,
    n_rows: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Group a COO by row id.

    Returns ``(c_sorted, v_sorted, counts, starts)`` where row ``r``'s
    ratings occupy ``[starts[r], starts[r+1])`` of the sorted arrays in
    original order (stable).  O(n) native path; NumPy argsort fallback.
    """
    n = len(val)
    row_ix = np.ascontiguousarray(row_ix, dtype=np.int32)
    col_ix = np.ascontiguousarray(col_ix, dtype=np.int32)
    val = np.ascontiguousarray(val, dtype=np.float32)
    if n and (row_ix.min() < 0 or row_ix.max() >= n_rows):
        # the C++ path does unchecked ++counts[row[i]]; keep the loud
        # Python-level failure the NumPy path had
        raise ValueError(
            f"row ids must be in [0, {n_rows}); got "
            f"[{int(row_ix.min())}, {int(row_ix.max())}]"
        )

    lib = _load()
    if lib is not None:
        counts = np.zeros(n_rows, dtype=np.int64)
        lib.pio_count_rows(row_ix, n, counts)
        starts = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        cursor = np.empty(n_rows, dtype=np.int64)
        c_sorted = np.empty(n, dtype=np.int32)
        v_sorted = np.empty(n, dtype=np.float32)
        lib.pio_sort_coo(
            row_ix, col_ix, val, n, n_rows, starts, cursor, c_sorted, v_sorted
        )
        return c_sorted, v_sorted, counts, starts

    order = np.argsort(row_ix, kind="stable")
    c_sorted = np.ascontiguousarray(col_ix[order])
    v_sorted = np.ascontiguousarray(val[order])
    counts = np.bincount(row_ix, minlength=n_rows).astype(np.int64)
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    return c_sorted, v_sorted, counts, starts


# number of per-event string-field slots emitted by pio_scan_events_jsonl
# (matches the Field enum in native/jsonl_scan.cpp)
_N_FIELDS = 8
(F_EVENT, F_ENTITY_TYPE, F_ENTITY_ID, F_TARGET_ENTITY_TYPE,
 F_TARGET_ENTITY_ID, F_PR_ID, F_EVENT_ID, F_PROPERTIES) = range(_N_FIELDS)


def scan_events_jsonl(data: bytes):
    """Native scan of a JSON-lines event buffer.

    Returns ``(n, field_off, field_len, event_ms, creation_ms, line_off,
    line_len, status)`` numpy arrays (sized n), or ``None`` when the
    native library is unavailable.  ``status[i] == 0`` means event ``i``'s
    storage-row fields were extracted natively; ``1`` means the caller
    must re-parse that line with the exact Python path (escapes, tags,
    validation failures, odd timestamps — parity by construction).
    """
    lib = _load()
    if lib is None or not hasattr(lib, "pio_scan_events_jsonl"):
        return None
    # one slot per newline upper-bounds the event count
    max_events = data.count(b"\n") + 1
    field_off = np.empty(max_events * _N_FIELDS, dtype=np.int64)
    field_len = np.empty(max_events * _N_FIELDS, dtype=np.int32)
    event_ms = np.empty(max_events, dtype=np.int64)
    creation_ms = np.empty(max_events, dtype=np.int64)
    line_off = np.empty(max_events, dtype=np.int64)
    line_len = np.empty(max_events, dtype=np.int32)
    status = np.empty(max_events, dtype=np.int32)
    consumed = ctypes.c_int64(0)
    n = lib.pio_scan_events_jsonl(
        data, len(data), max_events,
        field_off, field_len, event_ms, creation_ms,
        line_off, line_len, status, ctypes.byref(consumed),
    )
    n = int(n)
    return (
        n,
        field_off[: n * _N_FIELDS].reshape(n, _N_FIELDS),
        field_len[: n * _N_FIELDS].reshape(n, _N_FIELDS),
        event_ms[:n], creation_ms[:n], line_off[:n], line_len[:n],
        status[:n],
    )


def scan_ratings_sqlite(
    db_path: str, sql: str, binds, has_value_col: bool,
):
    """Fused scan + id-dictionary encode over one ratings SELECT.

    The caller builds ``sql`` (identifiers validated, every VALUE a
    ``?N`` placeholder filled from ``binds``) with the column contract
    ``entity_id, target_entity_id, event_time[, value]``;
    ``has_value_col=False`` is implicit-feedback mode (each row counts
    1.0).  Returns ``(u_codes i32[n], i_codes i32[n], values f64[n],
    times i64[n], user_ids object[n_users], item_ids object[n_items])``
    with codes in FIRST-SEEN dictionary order (callers remap to their
    preferred determinism), or None when the native lib is absent.
    Raises RuntimeError with sqlite's message on scan errors (e.g.
    json_extract hitting a NaN/Infinity token) so callers can fall
    back to the python path.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "pio_scan_ratings_sql"):
        return None
    binds = [b.encode() for b in binds]
    arr = (ctypes.c_char_p * len(binds))(*binds) if binds else None
    res = lib.pio_scan_ratings_sql(
        db_path.encode(), sql.encode(), arr, len(binds),
        1 if has_value_col else 0,
    )
    if not res:
        raise MemoryError("pio_scan_ratings allocation failed")
    try:
        rec = res.contents
        err = bytes(rec.err).split(b"\0", 1)[0]
        if err:
            raise RuntimeError(
                f"native ratings scan failed: {err.decode()}"
            )
        n = int(rec.n)
        u = np.ctypeslib.as_array(rec.u_codes, shape=(n,)).copy() \
            if n else np.empty(0, np.int32)
        i = np.ctypeslib.as_array(rec.i_codes, shape=(n,)).copy() \
            if n else np.empty(0, np.int32)
        v = np.ctypeslib.as_array(rec.values, shape=(n,)).copy() \
            if n else np.empty(0, np.float64)
        t = np.ctypeslib.as_array(rec.times, shape=(n,)).copy() \
            if n else np.empty(0, np.int64)

        def ids(arena_ptr, offs_ptr, count):
            count = int(count)
            if count == 0:
                return np.empty(0, dtype=object)
            offs = np.ctypeslib.as_array(offs_ptr, shape=(count + 1,))
            blob = ctypes.string_at(arena_ptr, int(offs[count]))
            out = np.empty(count, dtype=object)
            for k in range(count):
                out[k] = blob[offs[k]:offs[k + 1]].decode()
            return out

        user_ids = ids(rec.user_arena, rec.user_offs, rec.n_users)
        item_ids = ids(rec.item_arena, rec.item_offs, rec.n_items)
    finally:
        lib.pio_scan_ratings_free(res)
    return u, i, v, t, user_ids, item_ids
