"""Engine: the 4-component pipeline (DataSource -> Preparator -> Algorithm(s)
-> Serving) plus params plumbing.

Re-expression of reference `controller/Engine.scala` (class `Engine`
`:78-450`, object-level `train`/`eval` `:583-772`) and
`controller/EngineParams.scala:31-105`.  Differences by design:

* name -> class maps are explicit dict registries, not JVM reflection;
* the training substrate is a :class:`~predictionio_tpu.controller.base.
  WorkflowContext` (device mesh) instead of SparkContext;
* ``engine.json`` variant parsing (`jValueToEngineParams`,
  `Engine.scala:328-384`) lands on dataclass params via
  :func:`~predictionio_tpu.controller.params.extract_params`.
"""

from __future__ import annotations

import logging
from typing import Any, Generic, Mapping, Optional, Sequence, Tuple

from .base import (
    A,
    Algorithm,
    DataSource,
    EI,
    FirstServing,
    IdentityPreparator,
    P,
    PD,
    Preparator,
    Q,
    Serving,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    TD,
    WorkflowContext,
    instantiate,
)
from .params import Params, extract_params

logger = logging.getLogger(__name__)

__all__ = ["EngineParams", "Engine", "SimpleEngine", "EngineFactory"]


class EngineParams:
    """Named (DataSource, Preparator, [Algorithm], Serving) params 4-tuple
    (reference `controller/EngineParams.scala:31-83`)."""

    def __init__(
        self,
        data_source: Tuple[str, Optional[Params]] = ("", None),
        preparator: Tuple[str, Optional[Params]] = ("", None),
        algorithms: Sequence[Tuple[str, Optional[Params]]] = (("", None),),
        serving: Tuple[str, Optional[Params]] = ("", None),
    ):
        self.data_source = data_source
        self.preparator = preparator
        self.algorithms = list(algorithms)
        self.serving = serving

    def copy(self, **kw) -> "EngineParams":
        d = dict(
            data_source=self.data_source,
            preparator=self.preparator,
            algorithms=self.algorithms,
            serving=self.serving,
        )
        d.update(kw)
        return EngineParams(**d)

    def __repr__(self) -> str:
        return (
            f"EngineParams(ds={self.data_source}, prep={self.preparator}, "
            f"algos={self.algorithms}, serving={self.serving})"
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, EngineParams) and (
            self.data_source,
            self.preparator,
            self.algorithms,
            self.serving,
        ) == (other.data_source, other.preparator, other.algorithms, other.serving)

    def __hash__(self):
        return hash(
            (self.data_source, self.preparator, tuple(self.algorithms), self.serving)
        )


def _as_class_map(x) -> dict[str, type]:
    if isinstance(x, Mapping):
        return dict(x)
    return {"": x}


class Engine(Generic[TD, EI, PD, Q, P, A]):
    """The engine: component class maps + orchestration."""

    def __init__(
        self,
        data_source_class_map,
        preparator_class_map,
        algorithm_class_map,
        serving_class_map,
        params_validator=None,
    ):
        self.data_source_class_map = _as_class_map(data_source_class_map)
        self.preparator_class_map = _as_class_map(preparator_class_map)
        self.algorithm_class_map = _as_class_map(algorithm_class_map)
        self.serving_class_map = _as_class_map(serving_class_map)
        # optional callable(EngineParams) raising on CROSS-component
        # inconsistencies (per-component fields validate themselves in
        # their dataclasses; couplings like the recommendation
        # template's coo='local' <-> factorPlacement='sharded' need the
        # whole tuple).  Runs at params construction — config errors
        # surface at build/validate time, not after minutes of ingest
        self.params_validator = params_validator

    def validate_params(self, ep: EngineParams) -> None:
        if self.params_validator is not None:
            self.params_validator(ep)

    # -- component construction ------------------------------------------
    def _data_source(self, ep: EngineParams) -> DataSource:
        name, params = ep.data_source
        return instantiate(self._lookup(self.data_source_class_map, name,
                                        "datasource"), params)

    def _preparator(self, ep: EngineParams) -> Preparator:
        name, params = ep.preparator
        return instantiate(self._lookup(self.preparator_class_map, name,
                                        "preparator"), params)

    def _algorithms(self, ep: EngineParams) -> list[Algorithm]:
        return [
            instantiate(self._lookup(self.algorithm_class_map, name, "algorithm"),
                        params)
            for name, params in ep.algorithms
        ]

    def _serving(self, ep: EngineParams) -> Serving:
        name, params = ep.serving
        return instantiate(self._lookup(self.serving_class_map, name, "serving"),
                           params)

    @staticmethod
    def _lookup(cmap: dict[str, type], name: str, kind: str) -> type:
        if name in cmap:
            return cmap[name]
        if name == "" and len(cmap) == 1:
            return next(iter(cmap.values()))
        raise KeyError(
            f"{kind} '{name}' not found in engine definition; "
            f"existing name(s): {sorted(cmap)}"
        )

    # -- train (Engine.scala:135-167 + object Engine.train :583-670) -------
    def train(
        self,
        ctx: WorkflowContext,
        engine_params: EngineParams,
        workflow_params=None,
    ) -> list[Any]:
        _, models = self.train_components(ctx, engine_params, workflow_params)
        return models

    def train_components(
        self,
        ctx: WorkflowContext,
        engine_params: EngineParams,
        workflow_params=None,
        algo_indices: Optional[Sequence[int]] = None,
    ) -> Tuple[list[Algorithm], list[Any]]:
        """Train and return the *trained component instances* alongside the
        models (so persistence hooks see any state built during train).
        ``algo_indices`` restricts training to a subset of algorithms
        (partial retrain at deploy); the returned lists still cover only
        that subset, in index order.
        """
        from ..workflow.params import WorkflowParams

        wp = workflow_params or WorkflowParams()
        data_source = self._data_source(engine_params)
        preparator = self._preparator(engine_params)
        algorithms = self._algorithms(engine_params)
        if algo_indices is not None:
            algorithms = [algorithms[i] for i in algo_indices]

        td = data_source.read_training(ctx)
        if not wp.skip_sanity_check:
            _sanity(td, "training data")
        if wp.stop_after_read:
            raise StopAfterReadInterruption("stop-after-read requested")

        pd = preparator.prepare(ctx, td)
        if not wp.skip_sanity_check:
            _sanity(pd, "prepared data")
        if wp.stop_after_prepare:
            raise StopAfterPrepareInterruption("stop-after-prepare requested")

        models = []
        for i, algo in enumerate(algorithms):
            logger.info("training algorithm %d: %s", i, type(algo).__name__)
            model = algo.train(ctx, pd)
            if not wp.skip_sanity_check:
                _sanity(model, f"model {i}")
            models.append(model)
        return algorithms, models

    # -- eval (Engine.scala:289-326 + object Engine.eval :688-772) ----------
    def eval(
        self,
        ctx: WorkflowContext,
        engine_params: EngineParams,
        workflow_params=None,
    ) -> list[Tuple[Any, list[Tuple[Any, Any, Any]]]]:
        """Per eval set: (eval info, [(query, prediction, actual)])."""
        data_source = self._data_source(engine_params)
        preparator = self._preparator(engine_params)
        algorithms = self._algorithms(engine_params)
        serving = self._serving(engine_params)
        return self._eval_with(ctx, data_source, preparator, algorithms, serving)

    def _eval_with(self, ctx, data_source, preparator, algorithms, serving):
        eval_sets = data_source.read_eval(ctx)
        results = []
        for td, ei, qa in eval_sets:
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]
            results.append((ei, self._batch_serve(algorithms, models, serving, qa)))
        return results

    @staticmethod
    def _batch_serve(algorithms, models, serving, qa) -> list[Tuple[Any, Any, Any]]:
        queries = [q for q, _ in qa]
        per_algo = [
            algo.batch_predict(model, queries)
            for algo, model in zip(algorithms, models)
        ]
        out = []
        for i, (q, a) in enumerate(qa):
            preds = [pp[i] for pp in per_algo]
            out.append((q, serving.serve(q, preds), a))
        return out

    # -- batch eval over many candidates (BaseEngine.batchEval) -------------
    def batch_eval(
        self, ctx: WorkflowContext, engine_params_list: Sequence[EngineParams],
        workflow_params=None,
    ):
        return [
            (ep, self.eval(ctx, ep, workflow_params)) for ep in engine_params_list
        ]

    # -- engine.json variant parsing (Engine.scala:328-384) ------------------
    def _spec_to_params(
        self, spec: Mapping[str, Any], cmap: dict[str, type], kind: str
    ) -> Tuple[str, Optional[Params]]:
        name = spec.get("name", "")
        cls = self._lookup(cmap, name, kind)
        params_cls = getattr(cls, "params_class", None)
        raw = spec.get("params")
        if params_cls is None:
            return (name, None if raw is None else _DictParams(raw))
        return (name, extract_params(params_cls, raw))

    def params_from_variant(self, variant: Mapping[str, Any]) -> EngineParams:
        def comp(key: str, cmap: dict[str, type]) -> Tuple[str, Optional[Params]]:
            spec = variant.get(key)
            if spec is None:
                return ("", None)
            return self._spec_to_params(spec, cmap, key)

        algorithms = [
            self._spec_to_params(spec, self.algorithm_class_map, "algorithm")
            for spec in variant.get("algorithms", [])
        ] or [("", None)]

        ep = EngineParams(
            data_source=comp("datasource", self.data_source_class_map),
            preparator=comp("preparator", self.preparator_class_map),
            algorithms=algorithms,
            serving=comp("serving", self.serving_class_map),
        )
        self.validate_params(ep)
        return ep

    def params_from_instance(self, instance) -> EngineParams:
        """EngineInstance record -> the exact EngineParams it was trained
        with (deploy must serve with the trained params, not whatever the
        current engine.json says — reference `engineInstanceToEngineParams`,
        `controller/Engine.scala:386-450`)."""
        import json as _json

        def one(js: str, cmap: dict[str, type], kind: str):
            d = _json.loads(js) if js else {}
            if not d:
                return ("", None)
            ((name, params),) = d.items()
            return self._spec_to_params(
                {"name": name, "params": params}, cmap, kind
            )

        algorithms = [
            self._spec_to_params(
                {"name": name, "params": params},
                self.algorithm_class_map, "algorithm",
            )
            for spec in _json.loads(instance.algorithms_params or "[]")
            for name, params in spec.items()
        ] or [("", None)]
        return EngineParams(
            data_source=one(instance.data_source_params,
                            self.data_source_class_map, "datasource"),
            preparator=one(instance.preparator_params,
                           self.preparator_class_map, "preparator"),
            algorithms=algorithms,
            serving=one(instance.serving_params,
                        self.serving_class_map, "serving"),
        )


class _DictParams(Params):
    """Fallback params wrapper when an algorithm declares no params_class."""

    def __init__(self, d: Mapping[str, Any]):
        self.fields = dict(d)

    def __eq__(self, other):
        return isinstance(other, _DictParams) and self.fields == other.fields

    def __hash__(self):
        return hash(tuple(sorted(self.fields.items())))

    def __repr__(self):
        return f"_DictParams({self.fields})"


class SimpleEngine(Engine[TD, EI, TD, Q, P, A]):
    """DataSource + single algorithm, identity preparator, first serving
    (reference `EngineParams.scala:98-105`)."""

    def __init__(self, data_source_class, algorithm_class):
        super().__init__(
            data_source_class,
            IdentityPreparator,
            algorithm_class,
            FirstServing,
        )


class EngineFactory:
    """Engines are produced by zero-arg factories named in engine.json's
    ``engineFactory`` (reference `controller/EngineFactory.scala:29-34`);
    subclass or use any callable returning an Engine."""

    def apply(self) -> Engine:
        raise NotImplementedError

    def engine_params(self, key: str) -> EngineParams:
        raise KeyError(f"no engine params for key {key}")


def _sanity(obj: Any, what: str) -> None:
    # duck-typed: anything exposing sanity_check() participates
    # (SanityCheck subclassing is optional, unlike the reference trait)
    check = getattr(obj, "sanity_check", None)
    if callable(check):
        logger.info("sanity check on %s", what)
        check()
