"""Base controller abstractions: the typed contracts every engine component
implements.

Re-expression of the reference `core` base classes
(`/root/reference/core/src/main/scala/io/prediction/core/BaseAlgorithm.scala:29-52`,
`BaseDataSource.scala`, `BasePreparator.scala`, `BaseServing.scala`) and the
controller-level P/P2L/L taxonomy (`controller/{PAlgorithm,P2LAlgorithm,
LAlgorithm}.scala`).  The Spark trichotomy (distributed RDD model /
collected local model / local model) becomes an explicit
:class:`ModelPlacement` on one ``Algorithm`` base — SURVEY §2.7(3):

* ``DEVICE_SHARDED``  — model is a pytree of (possibly sharded) ``jax.Array``
  living in HBM (PAlgorithm analogue).
* ``HOST_REPLICATED`` — trained on device, small enough to serialize and
  replicate to every serving host (P2LAlgorithm analogue).
* ``HOST``            — pure host model (LAlgorithm analogue).

``Doer`` reflective construction (`core/AbstractDoer.scala:24-48`) becomes
:func:`instantiate`: try 1-arg (params) constructor, fall back to 0-arg.
"""

from __future__ import annotations

import enum
from typing import Any, Generic, Optional, Sequence, Tuple, TypeVar

from .params import EmptyParams, Params

__all__ = [
    "ModelPlacement",
    "WorkflowContext",
    "DataSource",
    "Preparator",
    "IdentityPreparator",
    "Algorithm",
    "Serving",
    "FirstServing",
    "AverageServing",
    "SanityCheck",
    "instantiate",
    "TrainingInterrupted",
    "StopAfterReadInterruption",
    "StopAfterPrepareInterruption",
]

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # evaluation info
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")    # model
Q = TypeVar("Q")    # query
P = TypeVar("P")    # predicted result
A = TypeVar("A")    # actual result


class ModelPlacement(enum.Enum):
    DEVICE_SHARDED = "device_sharded"
    HOST_REPLICATED = "host_replicated"
    HOST = "host"


class WorkflowContext:
    """Per-run handle passed to every controller — the SparkContext analogue.

    Carries the device mesh, the resolved storage, and run identity.  Created
    by the workflow drivers (`workflow/WorkflowContext.scala:25-44` parity:
    app name ``"PredictionIO <Mode>: <batch>"`` becomes :attr:`label`).
    """

    def __init__(self, mesh=None, storage=None, mode: str = "Training",
                 batch: str = "", verbose: bool = False):
        if mesh is None:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh()
        if storage is None:
            from ..storage.registry import get_storage

            storage = get_storage()
        self.mesh = mesh
        self.storage = storage
        self.mode = mode
        self.batch = batch
        self.verbose = verbose

    @property
    def label(self) -> str:
        return f"PredictionIO-TPU {self.mode}: {self.batch}"

    @property
    def n_devices(self) -> int:
        return self.mesh.size


class SanityCheck:
    """Data classes may implement this; the train workflow calls it on
    training data, prepared data and models
    (reference `controller/SanityCheck.scala:24-30`)."""

    def sanity_check(self) -> None:
        raise NotImplementedError


class DataSource(Generic[TD, EI, Q, A]):
    """Reads training and evaluation data from the event store
    (reference `controller/PDataSource.scala:33-60` / `LDataSource.scala`)."""

    params: Params = EmptyParams()

    def read_training(self, ctx: WorkflowContext) -> TD:
        raise NotImplementedError

    def read_eval(
        self, ctx: WorkflowContext
    ) -> Sequence[Tuple[TD, EI, Sequence[Tuple[Q, A]]]]:
        """Eval sets: (training data, eval info, (query, actual) pairs)."""
        return []


class Preparator(Generic[TD, PD]):
    """TD -> PD (reference `controller/PPreparator.scala`)."""

    params: Params = EmptyParams()

    def prepare(self, ctx: WorkflowContext, training_data: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(Preparator[TD, TD]):
    """Passthrough (reference `controller/IdentityPreparator.scala`)."""

    def prepare(self, ctx: WorkflowContext, training_data: TD) -> TD:
        return training_data


class Algorithm(Generic[PD, M, Q, P]):
    """Train + predict (reference `core/BaseAlgorithm.scala:29-52`).

    ``batch_predict`` is the evaluation path (reference
    ``batchPredictBase``); the default maps ``predict`` over queries, device
    algorithms override it with one batched XLA call.
    """

    params: Params = EmptyParams()
    placement: ModelPlacement = ModelPlacement.HOST_REPLICATED

    def train(self, ctx: WorkflowContext, prepared_data: PD) -> M:
        raise NotImplementedError

    def warmup(self, model: M,  # noqa: B027 — optional hook
               max_batch: int = 64) -> None:
        """Pre-compile the scoring path at deploy time so the first real
        query doesn't pay XLA compilation (the AOT-dispatch obligation of
        a <100 ms-class rec server; reference deploys are warm because
        JVM models need no compile).  ``max_batch`` is the serving
        micro-batcher's configured maximum, so batched warmups can cover
        every batch size its pow2 padding will dispatch."""

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(self, model: M, queries: Sequence[Q]) -> list[P]:
        return [self.predict(model, q) for q in queries]

    # -- persistence hooks (reference makePersistentModel / PersistentModel) --
    def save_model(self, ctx: WorkflowContext, model_id: str, model: M,
                   base_dir) -> Optional[dict]:
        """Custom persistence: return a manifest dict, or None to use the
        framework default (checkpoint pytree / pickle).  Reference:
        `controller/PersistentModel.scala:48-95`."""
        return None

    def load_model(self, ctx: WorkflowContext, model_id: str, manifest: dict,
                   base_dir) -> M:
        """Inverse of :meth:`save_model` when it returned a manifest."""
        raise NotImplementedError

    @property
    def persist_model(self) -> bool:
        """False -> model is not persisted and deploy retrains (parity with
        PAlgorithm-without-PersistentModel, `controller/Engine.scala:186-208`).
        Default True: always checkpoint (SURVEY §7 hard-part 6)."""
        return True


class Serving(Generic[Q, P]):
    """Combine predictions from all algorithms into one response
    (reference `controller/LServing.scala:27-39`)."""

    params: Params = EmptyParams()

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError


class FirstServing(Serving[Q, P]):
    """Serve the first algorithm's prediction
    (reference `controller/LFirstServing.scala:25-39`)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Average numeric predictions
    (reference `controller/LAverageServing.scala:25-41`)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)


class TrainingInterrupted(Exception):
    """Deliberate workflow interruption
    (reference `workflow/WorkflowUtils.scala:414-418`)."""


class StopAfterReadInterruption(TrainingInterrupted):
    pass


class StopAfterPrepareInterruption(TrainingInterrupted):
    pass


def _takes_params(cls: type) -> bool:
    import inspect

    try:
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError):
        return False
    args = [
        p
        for name, p in sig.parameters.items()
        if name != "self"
        and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(args) >= 1


def instantiate(cls: type, params: Optional[Params] = None) -> Any:
    """`Doer.apply` analogue (`core/AbstractDoer.scala:24-48`): construct
    ``cls`` with the params if its constructor takes one, else 0-arg; either
    way attach ``params``.  Arity is decided by signature inspection so a
    genuine TypeError inside a constructor propagates instead of being
    masked by a 0-arg retry."""
    if params is not None and _takes_params(cls):
        obj = cls(params)
    else:
        obj = cls()
    if params is not None:
        obj.params = params
    elif not hasattr(obj, "params"):
        obj.params = EmptyParams()
    return obj
