"""Controller DSL — the user-facing engine-building API
(reference `/root/reference/core/src/main/scala/io/prediction/controller/`)."""

from .base import (
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    ModelPlacement,
    Preparator,
    SanityCheck,
    Serving,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    TrainingInterrupted,
    WorkflowContext,
    instantiate,
)
from .engine import Engine, EngineFactory, EngineParams, SimpleEngine
from .evaluation import (
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from .fast_eval import FastEvalEngine
from .metrics import (
    AverageMetric,
    Metric,
    OptionAverageMetric,
    OptionStdevMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from .params import EmptyParams, Params, ParamsError, extract_params, params_to_json

__all__ = [
    "Algorithm",
    "AverageServing",
    "DataSource",
    "FirstServing",
    "IdentityPreparator",
    "ModelPlacement",
    "Preparator",
    "SanityCheck",
    "Serving",
    "StopAfterPrepareInterruption",
    "StopAfterReadInterruption",
    "TrainingInterrupted",
    "WorkflowContext",
    "instantiate",
    "Engine",
    "EngineParamsGenerator",
    "Evaluation",
    "MetricEvaluator",
    "MetricEvaluatorResult",
    "FastEvalEngine",
    "AverageMetric",
    "Metric",
    "OptionAverageMetric",
    "OptionStdevMetric",
    "StdevMetric",
    "SumMetric",
    "ZeroMetric",
    "EngineFactory",
    "EngineParams",
    "SimpleEngine",
    "EmptyParams",
    "Params",
    "ParamsError",
    "extract_params",
    "params_to_json",
]
