"""Controller DSL — the user-facing engine-building API
(reference `/root/reference/core/src/main/scala/io/prediction/controller/`)."""

from .base import (
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    ModelPlacement,
    Preparator,
    SanityCheck,
    Serving,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    TrainingInterrupted,
    WorkflowContext,
    instantiate,
)
from .engine import Engine, EngineFactory, EngineParams, SimpleEngine
from .params import EmptyParams, Params, ParamsError, extract_params, params_to_json

__all__ = [
    "Algorithm",
    "AverageServing",
    "DataSource",
    "FirstServing",
    "IdentityPreparator",
    "ModelPlacement",
    "Preparator",
    "SanityCheck",
    "Serving",
    "StopAfterPrepareInterruption",
    "StopAfterReadInterruption",
    "TrainingInterrupted",
    "WorkflowContext",
    "instantiate",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "SimpleEngine",
    "EmptyParams",
    "Params",
    "ParamsError",
    "extract_params",
    "params_to_json",
]
