"""Evaluation + hyperparameter sweep.

Re-expression of reference `controller/Evaluation.scala:32-96`,
`controller/MetricEvaluator.scala:144-221` and
`controller/EngineParamsGenerator`: score every EngineParams candidate with
the engine's eval pipeline, pick the argmax under ``metric.compare``, record
per-candidate logs, and emit one-liner/HTML/JSON renderings plus a
``best.json`` engine variant.
"""

from __future__ import annotations

import html as _html
import json
import logging
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional, Sequence

from .base import WorkflowContext
from .engine import Engine, EngineParams
from .metrics import Metric
from .params import params_to_json

logger = logging.getLogger(__name__)

__all__ = [
    "Evaluation",
    "EngineParamsGenerator",
    "MetricEvaluator",
    "MetricEvaluatorResult",
]


class EngineParamsGenerator:
    """Provides the candidate list (reference trait of the same name)."""

    engine_params_list: Sequence[EngineParams] = ()


class Evaluation:
    """Binds an engine with a metric (+ optional extra metrics)
    (reference `Evaluation.scala:66-96` ``engineMetric_=`` sugar)."""

    engine_params_list: Optional[Sequence[EngineParams]] = None

    def __init__(
        self,
        engine: Engine,
        metric: Metric,
        metrics: Sequence[Metric] = (),
        output_path: Optional[str] = "best.json",
        engine_params_list: Optional[Sequence[EngineParams]] = None,
    ):
        self.engine = engine
        self.metric = metric
        self.metrics = list(metrics)
        self.output_path = output_path
        if engine_params_list is not None:
            self.engine_params_list = list(engine_params_list)

    def run(
        self,
        ctx: WorkflowContext,
        engine_params_list: Sequence[EngineParams],
        workflow_params=None,
        parallelism: int = 1,
    ) -> "MetricEvaluatorResult":
        evaluator = MetricEvaluator(
            self.metric, self.metrics, output_path=self.output_path
        )
        return evaluator.evaluate(
            ctx, self.engine, engine_params_list, workflow_params,
            parallelism=parallelism,
        )


@dataclass
class MetricEvaluatorResult:
    """(reference `MetricEvaluator.scala:36-88`)"""

    metric_header: str
    other_metric_headers: list[str]
    best_score: float
    best_engine_params: Optional[EngineParams]
    best_index: int
    # per candidate: (engine_params, score, other_scores)
    results: list[tuple[EngineParams, Any, list[Any]]] = field(
        default_factory=list
    )

    def to_one_liner(self) -> str:
        return f"[{self.best_score}] {self.metric_header}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "otherMetricHeaders": self.other_metric_headers,
                "bestScore": self.best_score,
                "bestIndex": self.best_index,
                "bestEngineParams": (
                    _engine_params_json(self.best_engine_params)
                    if self.best_engine_params
                    else None
                ),
                "results": [
                    {
                        "engineParams": _engine_params_json(ep),
                        "score": score,
                        "otherScores": other,
                    }
                    for ep, score, other in self.results
                ],
            },
            indent=2,
        )

    def to_html(self) -> str:
        rows = "\n".join(
            "<tr><td>{}</td><td>{}</td><td><pre>{}</pre></td></tr>".format(
                _html.escape(str(score)),
                _html.escape(json.dumps(other)),
                _html.escape(
                    json.dumps(_engine_params_json(ep), indent=1)
                ),
            )
            for ep, score, other in self.results
        )
        return (
            "<html><body>"
            f"<h3>Best score: {_html.escape(str(self.best_score))} "
            f"({_html.escape(self.metric_header)})</h3>"
            f"<table border='1'><tr><th>{_html.escape(self.metric_header)}"
            f"</th><th>other metrics</th><th>engine params</th></tr>"
            f"{rows}</table></body></html>"
        )


def _engine_params_json(ep: EngineParams) -> dict:
    return {
        "datasource": {
            "name": ep.data_source[0],
            "params": params_to_json(ep.data_source[1]),
        },
        "preparator": {
            "name": ep.preparator[0],
            "params": params_to_json(ep.preparator[1]),
        },
        "algorithms": [
            {"name": n, "params": params_to_json(p)} for n, p in ep.algorithms
        ],
        "serving": {
            "name": ep.serving[0],
            "params": params_to_json(ep.serving[1]),
        },
    }


def _json_safe_score(score):
    """Manifest records are JSON lines; scores are usually floats but
    custom metrics may return anything comparable."""
    try:
        return float(score)
    except (TypeError, ValueError):
        return repr(score)


class MetricEvaluator:
    """Scores every candidate, argmax by ``metric.compare``
    (reference `MetricEvaluator.scala:177-221`)."""

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: Optional[str] = "best.json",
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path

    def _score_one(self, ctx, engine, ep, workflow_params, ix, total):
        import time as _time

        from ..obs import phase_span, tower

        t0 = _time.perf_counter()
        with phase_span("eval.sweep", attrs={"candidate": ix}):
            eval_out = engine.eval(ctx, ep, workflow_params)
            score = self.metric.calculate(ctx, eval_out)
            other = [
                m.calculate(ctx, eval_out) for m in self.other_metrics
            ]
        # pio-tower: an eval run's manifest appends one candidate
        # record per scored candidate — the sweep is replayable from
        # disk the way a training run's sweeps are
        tower.record_candidate(
            ix,
            score=_json_safe_score(score),
            metric=self.metric.header,
            seconds=round(_time.perf_counter() - t0, 6),
        )
        # streamed from here so the parallel sweep shows live progress too
        logger.info(
            "MetricEvaluator: candidate %d/%d -> %s = %s",
            ix + 1, total, self.metric.header, score,
        )
        return (ep, score, other)

    def evaluate(
        self,
        ctx: WorkflowContext,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
        workflow_params=None,
        parallelism: int = 1,
    ) -> MetricEvaluatorResult:
        """Score all candidates; ``parallelism > 1`` runs them from a
        thread pool (the reference's ``.par`` sweep,
        `MetricEvaluator.scala:183-192`).  Device work still serializes on
        the accelerator queue, but host-side reads/prep/metric math of one
        candidate overlap another's device time, and jitted executables
        are shared across threads (same shapes -> same cache entry).
        Results keep candidate order either way; storage backends and
        dispatch are thread-safe.  Sweeps through a ``FastEvalEngine`` are
        better run sequentially: its prefix cache dedupes shared pipeline
        stages only when candidates arrive in order."""
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        if parallelism > 1:
            from concurrent.futures import ThreadPoolExecutor

            from .fast_eval import FastEvalEngine

            if isinstance(engine, FastEvalEngine):
                raise ValueError(
                    "parallelism > 1 cannot run through a FastEvalEngine "
                    "(its prefix caches are not thread-safe); pass the "
                    "plain Engine, or use run_evaluation which unwraps it"
                )

            total = len(engine_params_list)
            with ThreadPoolExecutor(max_workers=parallelism) as ex:
                results = list(
                    ex.map(
                        lambda ix_ep: self._score_one(
                            ctx, engine, ix_ep[1], workflow_params,
                            ix_ep[0], total,
                        ),
                        enumerate(engine_params_list),
                    )
                )
        else:
            results = [
                self._score_one(
                    ctx, engine, ep, workflow_params, ix,
                    len(engine_params_list),
                )
                for ix, ep in enumerate(engine_params_list)
            ]

        # NaN-safe argmax: a NaN score never beats a finite one, and a
        # finite score always replaces a NaN incumbent (Metric.compare
        # returns -1 for any NaN comparison, which would otherwise let
        # a NaN first candidate win the whole sweep)
        def _is_nan(x) -> bool:
            return isinstance(x, float) and x != x

        best_ix, best_score = -1, None
        for ix, (_, score, _other) in enumerate(results):
            if (
                best_ix < 0
                or (_is_nan(best_score) and not _is_nan(score))
                or (
                    not _is_nan(score)
                    and self.metric.compare(score, best_score) > 0
                )
            ):
                best_ix, best_score = ix, score
        result = MetricEvaluatorResult(
            metric_header=self.metric.header,
            other_metric_headers=[m.header for m in self.other_metrics],
            best_score=best_score,
            best_engine_params=engine_params_list[best_ix],
            best_index=best_ix,
            results=results,
        )
        if self.output_path:
            self.save_engine_json(result, self.output_path)
        return result

    def save_engine_json(
        self, result: MetricEvaluatorResult, path: str | Path
    ) -> None:
        """Write the winning EngineParams as an engine.json-shaped variant
        (reference `MetricEvaluator.saveEngineJson:152-175`)."""
        ep = result.best_engine_params
        doc = {
            "id": "best",
            "description": f"best params from evaluation "
            f"({result.metric_header}={result.best_score})",
            **_engine_params_json(ep),
        }
        Path(path).write_text(json.dumps(doc, indent=2))
