"""Metric family for evaluation.

Re-expression of reference `controller/Metric.scala:36-218`: a ``Metric``
scores the full evaluation output (eval info + (query, prediction, actual)
triples per eval set); helper bases reduce per-point scores with one-pass
vectorized stats (the reference uses Spark ``StatCounter``; here the points
land in NumPy and reduce in one shot).
"""

from __future__ import annotations

from typing import Any, Generic, Optional, Sequence, Tuple, TypeVar

import numpy as np

from .base import A, EI, P, Q, WorkflowContext

R = TypeVar("R")

__all__ = [
    "Metric",
    "AverageMetric",
    "OptionAverageMetric",
    "StdevMetric",
    "OptionStdevMetric",
    "SumMetric",
    "QPAMetric",
    "ZeroMetric",
]

EvalData = Sequence[Tuple[Any, Sequence[Tuple[Any, Any, Any]]]]


class Metric(Generic[EI, Q, P, A, R]):
    """Base metric: ``calculate`` over all eval sets; ``compare`` orders
    results (default: larger is better — override for losses)."""

    def calculate(self, ctx: WorkflowContext, data: EvalData) -> R:
        raise NotImplementedError

    def compare(self, a: R, b: R) -> int:
        if a == b:
            return 0
        return 1 if a > b else -1

    @property
    def header(self) -> str:
        return type(self).__name__

    def __str__(self) -> str:
        return self.header


class _PointMetric(Metric[EI, Q, P, A, float]):
    """Shared machinery: map points -> floats, reduce with stats.

    ``strict=True`` raises when a point returns None (the non-Option metric
    variants); otherwise None points are skipped."""

    def calculate_point(self, query, predicted, actual) -> Optional[float]:
        raise NotImplementedError

    def _points(self, data: EvalData, strict: bool = False) -> np.ndarray:
        vals = []
        for _, qpa in data:
            for q, p, a in qpa:
                s = self.calculate_point(q, p, a)
                if s is None:
                    if strict:
                        raise ValueError(
                            f"{type(self).__name__}.calculate_point returned "
                            "None; use the Option* metric variant"
                        )
                    continue
                vals.append(s)
        return np.asarray(vals, dtype=np.float64)


class AverageMetric(_PointMetric):
    """Mean of per-point scores (reference `Metric.scala:87-100`).  A point
    returning None raises — use OptionAverageMetric for optional points."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data, strict=True)
        return float(arr.mean()) if len(arr) else float("nan")


class OptionAverageMetric(_PointMetric):
    """Mean over points that returned a value (`Metric.scala:112-125`)."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.mean()) if len(arr) else float("nan")


class StdevMetric(_PointMetric):
    """Population stdev of per-point scores (`Metric.scala:139`)."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data, strict=True)
        return float(arr.std()) if len(arr) else float("nan")


class OptionStdevMetric(_PointMetric):
    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.std()) if len(arr) else float("nan")


class SumMetric(_PointMetric):
    """Sum of per-point scores (`Metric.scala:193-211`)."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.sum())


class QPAMetric(Metric[EI, Q, P, A, R]):
    """Marker base for metrics consuming (Q, P, A) directly
    (`Metric.scala:216`)."""


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always 0 — placeholder metric (reference `ZeroMetric`)."""

    def calculate(self, ctx, data) -> float:
        return 0.0
