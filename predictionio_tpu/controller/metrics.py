"""Metric family for evaluation.

Re-expression of reference `controller/Metric.scala:36-218`: a ``Metric``
scores the full evaluation output (eval info + (query, prediction, actual)
triples per eval set); helper bases reduce per-point scores with one-pass
vectorized stats (the reference uses Spark ``StatCounter``; here the points
land in NumPy and reduce in one shot).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, Optional, Sequence, Tuple, TypeVar

import numpy as np

from .base import A, EI, P, Q, WorkflowContext

R = TypeVar("R")

__all__ = [
    "Metric",
    "ActualItems",
    "AverageMetric",
    "MAPatK",
    "OptionAverageMetric",
    "StdevMetric",
    "OptionStdevMetric",
    "SumMetric",
    "QPAMetric",
    "ZeroMetric",
]

EvalData = Sequence[Tuple[Any, Sequence[Tuple[Any, Any, Any]]]]


class Metric(Generic[EI, Q, P, A, R]):
    """Base metric: ``calculate`` over all eval sets; ``compare`` orders
    results (default: larger is better — override for losses)."""

    def calculate(self, ctx: WorkflowContext, data: EvalData) -> R:
        raise NotImplementedError

    def compare(self, a: R, b: R) -> int:
        if a == b:
            return 0
        return 1 if a > b else -1

    @property
    def header(self) -> str:
        return type(self).__name__

    def __str__(self) -> str:
        return self.header


class _PointMetric(Metric[EI, Q, P, A, float]):
    """Shared machinery: map points -> floats, reduce with stats.

    ``strict=True`` raises when a point returns None (the non-Option metric
    variants); otherwise None points are skipped."""

    def calculate_point(self, query, predicted, actual) -> Optional[float]:
        raise NotImplementedError

    def _points(self, data: EvalData, strict: bool = False) -> np.ndarray:
        vals = []
        for _, qpa in data:
            for q, p, a in qpa:
                s = self.calculate_point(q, p, a)
                if s is None:
                    if strict:
                        raise ValueError(
                            f"{type(self).__name__}.calculate_point returned "
                            "None; use the Option* metric variant"
                        )
                    continue
                vals.append(s)
        return np.asarray(vals, dtype=np.float64)


class AverageMetric(_PointMetric):
    """Mean of per-point scores (reference `Metric.scala:87-100`).  A point
    returning None raises — use OptionAverageMetric for optional points."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data, strict=True)
        return float(arr.mean()) if len(arr) else float("nan")


class OptionAverageMetric(_PointMetric):
    """Mean over points that returned a value (`Metric.scala:112-125`)."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.mean()) if len(arr) else float("nan")


class StdevMetric(_PointMetric):
    """Population stdev of per-point scores (`Metric.scala:139`)."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data, strict=True)
        return float(arr.std()) if len(arr) else float("nan")


class OptionStdevMetric(_PointMetric):
    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.std()) if len(arr) else float("nan")


class SumMetric(_PointMetric):
    """Sum of per-point scores (`Metric.scala:193-211`)."""

    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.sum())


class QPAMetric(Metric[EI, Q, P, A, R]):
    """Marker base for metrics consuming (Q, P, A) directly
    (`Metric.scala:216`)."""


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always 0 — placeholder metric (reference `ZeroMetric`)."""

    def calculate(self, ctx, data) -> float:
        return 0.0


# -- ranking metrics (pio-lens satellite; ROADMAP 4(b)) ---------------------


@dataclass(frozen=True)
class ActualItems:
    """Ranking-eval ground truth: the held-out relevant item set for
    one query (the analogue of ``ActualRating`` for top-k engines)."""

    items: tuple[str, ...]


class MAPatK(_PointMetric):
    """Mean Average Precision at k over ranked predictions.

    Per point: the prediction's ordered ``item_scores`` are cut at k
    and scored against the actual's relevant item SET with the
    standard AP@k —

        ``sum_i( precision@i * rel(i) ) / min(k, |relevant|)``

    (reference e2's ranking metrics family; the normalizer caps at k
    so a query with more relevant items than the cutoff can still
    score 1.0).  Points with an empty relevant set are skipped
    (Option semantics — nothing to rank against is not a zero)."""

    def __init__(self, k: int = 10):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = k

    @property
    def header(self) -> str:
        return f"MAP@{self.k}"

    @staticmethod
    def _ranked_items(predicted) -> list:
        scores = getattr(predicted, "item_scores", None)
        if scores is None and isinstance(predicted, dict):
            scores = predicted.get("itemScores", ())
        out = []
        for s in scores or ():
            item = getattr(s, "item", None)
            if item is None and isinstance(s, dict):
                item = s.get("item")
            out.append(str(item))
        return out

    def calculate_point(self, query, predicted, actual) -> Optional[float]:
        relevant = set(getattr(actual, "items", ()) or ())
        if not relevant:
            return None
        ranked = self._ranked_items(predicted)[: self.k]
        hits = 0
        ap = 0.0
        for i, item in enumerate(ranked):
            if item in relevant:
                hits += 1
                ap += hits / (i + 1)
        return ap / min(self.k, len(relevant))

    def calculate(self, ctx, data) -> float:
        arr = self._points(data)
        return float(arr.mean()) if len(arr) else float("nan")
