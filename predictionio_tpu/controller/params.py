"""Typed controller parameters + JSON extraction.

Replaces the reference's `Params` marker trait
(`/root/reference/core/src/main/scala/io/prediction/controller/Params.scala:23-31`)
and the json4s/gson reflection extractor
(`workflow/WorkflowUtils.scala:129-208`): components declare a ``@dataclass``
params type, and :func:`extract_params` builds it from an ``engine.json``
params dict — recursively for nested dataclasses, with unknown-key detection
(stricter than the reference, which silently ignored typos).
"""

from __future__ import annotations

import dataclasses
import types
import typing
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Type, TypeVar, Union, get_args, get_origin

__all__ = ["Params", "EmptyParams", "extract_params", "params_to_json", "ParamsError"]


@dataclass(frozen=True)
class Params:
    """Marker base for controller parameter dataclasses."""


@dataclass(frozen=True)
class EmptyParams(Params):
    pass


class ParamsError(ValueError):
    pass


P = TypeVar("P")


def _convert(value: Any, typ: Any, path: str) -> Any:
    origin = get_origin(typ)
    if typ is Any or typ is None or typ is type(None):
        return value
    if origin is Union or origin is types.UnionType:  # Optional[X] and X | None
        args = [a for a in get_args(typ) if a is not type(None)]
        if value is None:
            return None
        if len(args) == 1:
            return _convert(value, args[0], path)
        return value
    if dataclasses.is_dataclass(typ):
        if not isinstance(value, Mapping):
            raise ParamsError(f"{path}: expected object for {typ.__name__}")
        return extract_params(typ, value, _path=path)
    if origin in (list, tuple):
        args = get_args(typ)
        if not isinstance(value, (list, tuple)):
            raise ParamsError(f"{path}: expected array")
        if origin is tuple and args and args[-1] is not Ellipsis:
            return tuple(
                _convert(v, t, f"{path}[{i}]")
                for i, (v, t) in enumerate(zip(value, args))
            )
        elem = args[0] if args else Any
        out = [_convert(v, elem, f"{path}[{i}]") for i, v in enumerate(value)]
        return tuple(out) if origin is tuple else out
    if origin is dict:
        kt, vt = (get_args(typ) + (Any, Any))[:2]
        return {
            _convert(k, kt, path): _convert(v, vt, f"{path}.{k}")
            for k, v in value.items()
        }
    if typ is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParamsError(f"{path}: expected number, got {value!r}")
        return float(value)
    if typ is int:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParamsError(f"{path}: expected int, got {value!r}")
        if isinstance(value, float) and not value.is_integer():
            raise ParamsError(f"{path}: expected int, got {value!r}")
        return int(value)
    if typ is bool and not isinstance(value, bool):
        raise ParamsError(f"{path}: expected bool, got {value!r}")
    if typ is str and not isinstance(value, str):
        raise ParamsError(f"{path}: expected string, got {value!r}")
    return value


def _snake(s: str) -> str:
    """camelCase -> snake_case, inserting '_' only at lower/digit->upper
    boundaries so acronym runs survive ('appURL' -> 'app_url')."""
    out = []
    for i, ch in enumerate(s):
        if ch.isupper():
            prev_lower = i > 0 and (s[i - 1].islower() or s[i - 1].isdigit())
            next_lower = i + 1 < len(s) and s[i + 1].islower()
            if prev_lower or (i > 0 and s[i - 1].isupper() and next_lower):
                out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def extract_params(
    cls: Type[P], json_dict: Optional[Mapping[str, Any]], _path: str = "params"
) -> P:
    """Build a params dataclass from a JSON dict (engine.json ``params`` key).

    Missing fields use dataclass defaults; missing required fields and unknown
    keys raise :class:`ParamsError`.  Reference engine.json files use
    camelCase keys (and reserved words like ``lambda``): camelCase is
    auto-converted to snake_case, and classes may declare
    ``__param_aliases__ = {"lambda": "lam"}`` for the rest.
    """
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"{cls!r} is not a params dataclass")
    json_dict = dict(json_dict or {})
    aliases = getattr(cls, "__param_aliases__", {})
    field_names = {f.name for f in dataclasses.fields(cls) if f.init}
    renamed = {}
    for k, v in json_dict.items():
        if k in aliases:
            k = aliases[k]
        elif k not in field_names and _snake(k) in field_names:
            k = _snake(k)
        if k in renamed:
            raise ParamsError(f"{_path}: duplicate key '{k}' after aliasing")
        renamed[k] = v
    json_dict = renamed
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    fields = {f.name: f for f in dataclasses.fields(cls) if f.init}
    unknown = set(json_dict) - set(fields)
    if unknown:
        raise ParamsError(
            f"{_path}: unknown key(s) {sorted(unknown)} for {cls.__name__} "
            f"(expected {sorted(fields)})"
        )
    for name, f in fields.items():
        if name in json_dict:
            kwargs[name] = _convert(json_dict[name], hints.get(name, Any),
                                    f"{_path}.{name}")
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        ):
            raise ParamsError(f"{_path}: missing required field '{name}' "
                              f"for {cls.__name__}")
    try:
        return cls(**kwargs)
    except (TypeError, ValueError) as e:
        raise ParamsError(f"{_path}: cannot construct {cls.__name__}: {e}") from e


def params_to_json(p: Any) -> dict[str, Any]:
    """Params dataclass -> JSON-able dict (for instance records)."""
    if dataclasses.is_dataclass(p) and not isinstance(p, type):
        return dataclasses.asdict(p)
    if isinstance(p, Mapping):
        return dict(p)
    fields = getattr(p, "fields", None)  # _DictParams fallback wrapper
    if isinstance(fields, dict):
        return dict(fields)
    return {}
