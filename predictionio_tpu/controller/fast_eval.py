"""FastEvalEngine: prefix-memoized evaluation across a params sweep.

Re-expression of reference `controller/FastEvalEngine.scala:45-330`: during
``batch_eval`` over many EngineParams candidates, pipeline stages whose
*params prefix* matches a previous candidate reuse its results instead of
recomputing — a sweep varying only algorithm params re-reads and re-prepares
nothing.  Cache keys mirror the reference's ``DataSourcePrefix`` /
``PreparatorPrefix`` / ``AlgorithmsPrefix`` / ``ServingPrefix``.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any

from .base import WorkflowContext
from .engine import Engine, EngineParams

logger = logging.getLogger(__name__)

__all__ = ["FastEvalEngine"]


_OPAQUE = itertools.count()
# __slots__ objects can't carry the token; pin them (strong ref) so their
# address can never be reused by a different params object while this
# process lives — id() is then a safe identity key.  Bounded by the number
# of distinct slotted-no-repr params candidates ever evaluated (rare).
_OPAQUE_PINNED: dict[int, tuple[int, Any]] = {}


def _key(named_params) -> Any:
    """Hashable key for a (name, Params) pair or list thereof.

    Params without value semantics (no custom ``__repr__`` — the default
    one embeds a reusable memory address) key on OBJECT IDENTITY via a
    token stamped on the instance: the same object keeps hitting the
    cache (trivially equal to itself), but a different object never
    aliases it even when the allocator reuses the address — the
    reference's "not cached when isEqual is not implemented" rule
    (`FastEvalEngineTest.scala:131`).  Keying on the raw default repr
    would silently alias two different candidates on address reuse.
    """
    if isinstance(named_params, list):
        return tuple(_key(x) for x in named_params)
    name, params = named_params
    if params is not None and type(params).__repr__ is object.__repr__:
        try:
            tok = params.__dict__.setdefault(
                "_pio_opaque_token", next(_OPAQUE)
            )
        except AttributeError:  # __slots__ object: pin + identity token
            tok = _OPAQUE_PINNED.setdefault(
                id(params), (next(_OPAQUE), params)
            )[0]
        return (name, f"opaque-{tok}")
    return (name, repr(params))


class FastEvalEngine(Engine):
    """Evaluation-only engine with pipeline-prefix caching.

    Not for training/deploy (reference restricts it the same way:
    `FastEvalEngine.scala:297-330`).
    """

    def __init__(self, *args, **kwargs):
        if args and isinstance(args[0], Engine) and len(args) == 1 and not kwargs:
            e = args[0]
            super().__init__(
                e.data_source_class_map,
                e.preparator_class_map,
                e.algorithm_class_map,
                e.serving_class_map,
            )
        else:
            super().__init__(*args, **kwargs)
        self._ds_cache: dict = {}
        self._prep_cache: dict = {}
        self._algo_cache: dict = {}
        # hit/miss counters (FastEvalEngineTest asserts on these)
        self.stats = {"ds": 0, "prep": 0, "algo": 0}

    # -- cached stages ----------------------------------------------------
    def _get_eval_sets(self, ctx, ep: EngineParams):
        key = _key(ep.data_source)
        if key not in self._ds_cache:
            self.stats["ds"] += 1
            ds = self._data_source(ep)
            self._ds_cache[key] = ds.read_eval(ctx)
        return self._ds_cache[key]

    def _get_prepared(self, ctx, ep: EngineParams):
        key = (_key(ep.data_source), _key(ep.preparator))
        if key not in self._prep_cache:
            self.stats["prep"] += 1
            prep = self._preparator(ep)
            eval_sets = self._get_eval_sets(ctx, ep)
            self._prep_cache[key] = [
                (prep.prepare(ctx, td), ei, qa) for td, ei, qa in eval_sets
            ]
        return self._prep_cache[key]

    def _get_models(self, ctx, ep: EngineParams):
        key = (
            _key(ep.data_source),
            _key(ep.preparator),
            _key(list(ep.algorithms)),
        )
        if key not in self._algo_cache:
            self.stats["algo"] += 1
            algorithms = self._algorithms(ep)
            prepared = self._get_prepared(ctx, ep)
            self._algo_cache[key] = (
                algorithms,
                [
                    [algo.train(ctx, pd) for algo in algorithms]
                    for pd, _, _ in prepared
                ],
            )
        return self._algo_cache[key]

    # -- eval using the caches --------------------------------------------
    def eval(self, ctx: WorkflowContext, engine_params: EngineParams,
             workflow_params=None):
        serving = self._serving(engine_params)
        prepared = self._get_prepared(ctx, engine_params)
        algorithms, per_set_models = self._get_models(ctx, engine_params)
        results = []
        for (pd, ei, qa), models in zip(prepared, per_set_models):
            results.append(
                (ei, self._batch_serve(algorithms, models, serving, qa))
            )
        return results

    def clear_cache(self) -> None:
        self._ds_cache.clear()
        self._prep_cache.clear()
        self._algo_cache.clear()
        self.stats = {"ds": 0, "prep": 0, "algo": 0}
