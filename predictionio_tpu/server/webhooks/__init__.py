"""Webhooks framework: third-party payloads -> validated events.

Re-expression of reference `data/webhooks/` (`JsonConnector.scala`,
`FormConnector.scala`, `ConnectorUtil.scala`, registry in
`api/WebhooksConnectors.scala`): connectors are pure functions from
provider payloads to event-JSON; :func:`to_event` pushes them through the
standard wire-format validation.
"""

from __future__ import annotations

from typing import Any, Mapping

from ...storage.event import Event

__all__ = [
    "ConnectorError",
    "JsonConnector",
    "FormConnector",
    "to_event",
    "JSON_CONNECTORS",
    "FORM_CONNECTORS",
]


class ConnectorError(ValueError):
    """(reference `ConnectorException`)"""


class JsonConnector:
    """JSON-body webhook -> event JSON (reference `JsonConnector.scala`)."""

    def to_event_json(self, data: Mapping[str, Any]) -> dict:
        raise NotImplementedError


class FormConnector:
    """Form-encoded webhook -> event JSON (reference `FormConnector.scala`)."""

    def to_event_json(self, data: Mapping[str, str]) -> dict:
        raise NotImplementedError


def to_event(connector, data) -> Event:
    """connector payload -> validated Event
    (reference `ConnectorUtil.toEvent`)."""
    event_json = connector.to_event_json(data)
    try:
        return Event.from_json(event_json)
    except Exception as e:
        raise ConnectorError(
            f"connector produced invalid event JSON: {e}"
        ) from e


from .segmentio import SegmentIOConnector  # noqa: E402
from .mailchimp import MailChimpConnector  # noqa: E402
from .example import (  # noqa: E402
    ExampleFormConnector,
    ExampleJsonConnector,
)

JSON_CONNECTORS: dict[str, JsonConnector] = {
    "segmentio": SegmentIOConnector(),
    "examplejson": ExampleJsonConnector(),
}
FORM_CONNECTORS: dict[str, FormConnector] = {
    "mailchimp": MailChimpConnector(),
    "exampleform": ExampleFormConnector(),
}
