"""Example connectors: templates for writing custom webhook adapters.

Semantics mirror the reference's test-fixture connectors
(`data/src/test/.../webhooks/examplejson`, `exampleform`): a minimal
field mapping from a third-party payload into the event wire format.
Registered as ``examplejson`` / ``exampleform`` so
``POST /webhooks/examplejson.json`` works out of the box as a starting
point.
"""

from __future__ import annotations

from typing import Any, Mapping

from . import ConnectorError, FormConnector, JsonConnector

__all__ = ["ExampleJsonConnector", "ExampleFormConnector"]


class ExampleJsonConnector(JsonConnector):
    """Expects ``{"type": ..., "userId": ..., "timestamp": ...,
    ["itemId": ...], ...extra}`` and maps extras into properties."""

    _RESERVED = {"type", "userId", "itemId", "timestamp"}

    def to_event_json(self, data: Mapping[str, Any]) -> dict:
        for required in ("type", "userId", "timestamp"):
            if required not in data:
                raise ConnectorError(
                    f"examplejson payload missing {required!r}"
                )
        out: dict[str, Any] = {
            "event": str(data["type"]),
            "entityType": "user",
            "entityId": str(data["userId"]),
            "eventTime": str(data["timestamp"]),
        }
        if data.get("itemId") is not None:
            out["targetEntityType"] = "item"
            out["targetEntityId"] = str(data["itemId"])
        props = {k: v for k, v in data.items() if k not in self._RESERVED}
        if props:
            out["properties"] = props
        return out


class ExampleFormConnector(FormConnector):
    """Form-encoded variant: ``type``, ``userId``, ``timestamp`` fields,
    everything else becomes string properties."""

    _RESERVED = {"type", "userId", "itemId", "timestamp"}

    def to_event_json(self, data: Mapping[str, str]) -> dict:
        json_like: dict[str, Any] = dict(data)
        return ExampleJsonConnector().to_event_json(json_like)
