"""Segment.io webhook connector
(reference `data/webhooks/segmentio/SegmentIOConnector.scala:25-71`):
supports the ``identify`` call type."""

from __future__ import annotations

from typing import Any, Mapping


class SegmentIOConnector:
    def to_event_json(self, data: Mapping[str, Any]) -> dict:
        from . import ConnectorError

        typ = data.get("type")
        if typ is None:
            raise ConnectorError("missing 'type' field in segment.io data")
        if typ != "identify":
            raise ConnectorError(
                f"Cannot convert unknown type {typ} to event JSON."
            )
        user_id = data.get("userId") or data.get("user_id")
        if not user_id:
            raise ConnectorError("missing 'userId' in segment.io identify")
        out = {
            "event": typ,
            "entityType": "user",
            "entityId": str(user_id),
            "properties": {
                "context": data.get("context", {}),
                "traits": data.get("traits", {}),
            },
        }
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out
