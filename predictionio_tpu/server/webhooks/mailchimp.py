"""MailChimp webhook connector
(reference `data/webhooks/mailchimp/MailChimpConnector.scala`): supports the
``subscribe`` form callback; MailChimp timestamps (``yyyy-MM-dd HH:mm:ss``
UTC) are converted to ISO8601."""

from __future__ import annotations

import datetime as _dt
from typing import Mapping

from ...storage.event import UTC, format_time


class MailChimpConnector:
    @staticmethod
    def _parse_time(s: str) -> _dt.datetime:
        return _dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=UTC)

    def to_event_json(self, data: Mapping[str, str]) -> dict:
        from . import ConnectorError

        typ = data.get("type")
        if typ is None:
            raise ConnectorError("The field 'type' is required for MailChimp data.")
        if typ != "subscribe":
            raise ConnectorError(
                f"Cannot convert unknown MailChimp data type {typ} to event JSON"
            )
        try:
            event_time = format_time(self._parse_time(data["fired_at"]))
            return {
                "event": "subscribe",
                "entityType": "user",
                "entityId": data["data[id]"],
                "targetEntityType": "list",
                "targetEntityId": data["data[list_id]"],
                "eventTime": event_time,
                "properties": {
                    "email": data["data[email]"],
                    "email_type": data["data[email_type]"],
                    "merges": {
                        "EMAIL": data["data[merges][EMAIL]"],
                        "FNAME": data["data[merges][FNAME]"],
                        "LNAME": data["data[merges][LNAME]"],
                        "INTERESTS": data.get("data[merges][INTERESTS]", ""),
                    },
                    "ip_opt": data["data[ip_opt]"],
                    "ip_signup": data["data[ip_signup]"],
                },
            }
        except KeyError as e:
            raise ConnectorError(
                f"missing MailChimp field {e.args[0]}"
            ) from e
