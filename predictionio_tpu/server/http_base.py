"""Shared HTTP server plumbing for all four servers (event, serving, admin,
dashboard): bind/serve/stop lifecycle, a JSON reply helper, and the
common ``GET /metrics`` Prometheus exposition mount (pio-obs)."""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ..obs import TRACE_HEADER, metrics_enabled, render_prometheus

PROMETHEUS_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Base handler: HTTP/1.1 keep-alive + JSON/body helpers."""

    protocol_version = "HTTP/1.1"
    # the reply is two send() calls (buffered headers, then body);
    # without TCP_NODELAY, Nagle holds the body segment until the
    # client's delayed ACK — measured as a ~40 ms stall on EVERY
    # keep-alive POST (pio-pulse loadgen found it; connection-per-
    # request clients like urllib never hit it, which is why the
    # earlier benches didn't see it)
    disable_nagle_algorithm = True
    server_logger = None  # subclasses set a logging.Logger

    def log_message(self, fmt, *args):
        if self.server_logger is not None:
            self.server_logger.debug(fmt, *args)

    def _serve_metrics(self) -> bool:
        """Answer the common observability mounts — ``GET /metrics``
        (Prometheus exposition), ``GET /debug/xray`` (compiler/device/
        flight-recorder JSON, pio-xray), ``GET /debug/train`` (training
        run progress + manifest history, pio-tower) and ``GET
        /debug/profile`` (blocking on-demand jax.profiler capture,
        pio-pulse) — from the process-wide registry.  Every server's
        ``do_GET`` tries this first, so all four HTTP surfaces expose
        the same set without per-server code.  Returns True when the
        request was handled."""
        path = urllib.parse.urlparse(self.path).path
        if path not in ("/metrics", "/debug/xray", "/debug/train",
                        "/debug/profile"):
            return False
        if not metrics_enabled():
            self._reply(404, {"message": "metrics disabled (--no-metrics)"})
            return True
        if path == "/debug/xray":
            from ..obs.xray import xray_payload

            self._reply(200, xray_payload())
            return True
        if path == "/debug/train":
            from ..obs.tower import train_payload

            self._reply(200, train_payload())
            return True
        if path == "/debug/profile":
            self._serve_profile()
            return True
        self._reply(200, render_prometheus().encode(),
                    ctype=PROMETHEUS_CTYPE)
        return True

    def _serve_profile(self) -> None:
        """``GET /debug/profile?seconds=S``: capture a jax.profiler
        trace into ``$PIO_TPU_HOME/telemetry/profiles/`` with pulse
        segments bridged as TraceAnnotations, and answer the artifact
        manifest.  Blocks this handler thread for S (clamped) seconds —
        the other ThreadingHTTPServer threads keep serving, which is
        exactly what a live capture wants to observe."""
        from ..obs import timeline

        qs = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        try:
            seconds = float(qs.get("seconds", ["2"])[0])
        except ValueError:
            self._reply(400, {
                "message": f"bad seconds: {qs['seconds'][0]!r}"
            })
            return
        try:
            self._reply(200, timeline.capture_profile(seconds))
        except timeline.ProfileBusy as e:
            self._reply(409, {"message": str(e)})
        except Exception as e:
            self._reply(500, {
                "message": f"profile capture failed: {e}"
            })

    def _trace_id(self) -> Optional[str]:
        """The request's propagated trace id (``X-PIO-Trace``), if any."""
        tid = self.headers.get(TRACE_HEADER)
        return tid.strip() if tid else None

    def _reply(self, code: int, payload: Any,
               ctype: str = "application/json") -> None:
        body = (
            payload
            if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in getattr(self, "extra_headers", ()):
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""


class HTTPServerBase:
    """Mixin providing the bind/serve/background/stop lifecycle.

    Subclasses implement ``_make_handler()`` and expose ``host``/``port``
    attributes (port 0 -> ephemeral, re-read after bind).  Binding happens
    in the caller's thread so bind errors (port in use) surface as
    exceptions instead of hanging a background thread.
    """

    host: str
    port: int
    _httpd: Optional[ThreadingHTTPServer] = None

    def _make_handler(self):
        raise NotImplementedError

    bind_retries = 3  # MasterActor retries the spray bind 3x in the reference

    def _bind(self) -> None:
        import errno
        import time

        retries = max(1, self.bind_retries)
        for attempt in range(retries):
            try:
                self._httpd = ThreadingHTTPServer(
                    (self.host, self.port), self._make_handler()
                )
                break
            except OSError as e:
                # only a busy port is transient (a stale server shutting
                # down); permission/addr errors fail immediately
                if e.errno != errno.EADDRINUSE or attempt + 1 >= retries:
                    raise
                time.sleep(1.0)
        self.port = self._httpd.server_address[1]

    _serving: bool = False

    def serve_forever(self) -> None:
        if self._httpd is None:
            self._bind()
        self._serving = True
        self._httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        self._bind()
        self._serving = True
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        if self._httpd is not None:
            if self._serving:
                # shutdown() handshakes with the serve loop; calling it on
                # a bound-but-never-served server would block forever
                self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._serving = False
