"""Shared HTTP server plumbing for all four servers (event, serving, admin,
dashboard): bind/serve/stop lifecycle, a JSON reply helper, and the
common ``GET /metrics`` Prometheus exposition mount (pio-obs)."""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ..obs import (
    HTTP_CONN_REJECTED,
    TRACE_HEADER,
    metrics_enabled,
    render_prometheus,
)

PROMETHEUS_CTYPE = "text/plain; version=0.0.4; charset=utf-8"

# per-server default for the concurrent-connection cap (pio-surge): a
# slow-loris client opening sockets used to pin one thread EACH on the
# threading edge, unbounded; both edges now shed connection attempts
# past the cap with a structured 503 + Connection: close
DEFAULT_MAX_CONNECTIONS = 512


class CappedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a bound on concurrent connections.

    Each accepted connection (keep-alive included) holds one handler
    thread until it closes; past ``max_connections`` of them, further
    connections are answered with a minimal structured 503 and closed
    instead of spawning thread number cap+1.  The refusal is written
    inline on the listener thread — a few hundred bytes into a fresh
    socket's send buffer never blocks.
    """

    def __init__(self, server_address, handler_class,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 server_name: str = "serving"):
        self.max_connections = max_connections
        self._conn_sema = threading.BoundedSemaphore(max_connections)
        self._m_rejected = HTTP_CONN_REJECTED.labels(server=server_name)
        super().__init__(server_address, handler_class)

    def process_request(self, request, client_address):
        if not self._conn_sema.acquire(blocking=False):
            self._m_rejected.inc()
            self._refuse(request)
            return
        try:
            super().process_request(request, client_address)
        except BaseException:
            self._conn_sema.release()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._conn_sema.release()

    def _refuse(self, request) -> None:
        body = json.dumps({
            "message": "connection limit reached",
            "error": "TooManyConnections",
        }).encode()
        try:
            request.sendall(
                b"HTTP/1.1 503 Service Unavailable\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Retry-After: 1\r\nConnection: close\r\n\r\n" + body
            )
        except OSError:
            pass
        self.shutdown_request(request)


OBS_PATHS = ("/metrics", "/debug/xray", "/debug/train", "/debug/profile",
             "/debug/flight", "/debug/fleet", "/debug/pprof")


def observability_response(path: str, query: str = ""):
    """Answer the common observability mounts shared by every server
    (both edges): returns ``(code, payload, ctype)`` or ``None`` when
    ``path`` is not an observability mount.  ``/debug/profile`` BLOCKS
    for the capture duration — event-loop callers must run this off
    the loop (the serving edge routes all GETs through its aux pool)."""
    if path not in OBS_PATHS:
        return None
    if not metrics_enabled():
        return 404, {"message": "metrics disabled (--no-metrics)"}, None
    if path == "/debug/xray":
        from ..obs.xray import xray_payload

        return 200, xray_payload(), None
    if path == "/debug/train":
        from ..obs.tower import train_payload

        return 200, train_payload(), None
    if path == "/debug/flight":
        # pio-lens: the process flight recorder, addressable by trace
        # id — the router's /debug/fleet lazily joins a worst-N entry
        # with the serving replica's own record through this mount
        from ..obs import get_flight_recorder

        qs = urllib.parse.parse_qs(query)
        trace = qs.get("trace", [None])[0]
        fr = get_flight_recorder()
        if trace:
            return 200, {"record": fr.record_for(trace)}, None
        spans = qs.get("spans", ["0"])[0] not in ("0", "", "false")
        return 200, fr.summary(spans=spans), None
    if path == "/debug/fleet":
        # answered for real by a RouterServer (its own handler builds
        # the payload); on other servers this mount reports whether a
        # router lives in-process (the dashboard's fleet.html reads it)
        from ..obs import fleet

        payload = fleet.fleet_payload()
        if payload is None:
            return 404, {"message": "no router in this process "
                         "(curl the router's /debug/fleet)"}, None
        return 200, payload, None
    if path == "/debug/pprof":
        # pio-scope: collapsed-stack text from the always-on sampler's
        # rolling ring — answers instantly from history (safe on the
        # event loop, unlike /debug/profile's capture-for-S-seconds)
        from ..obs import scope

        qs = urllib.parse.parse_qs(query)
        try:
            seconds = float(qs.get("seconds", ["60"])[0])
        except ValueError:
            return 400, {"message":
                         f"bad seconds: {qs['seconds'][0]!r}"}, None
        state = qs.get("state", [None])[0]
        if state in ("", "all"):
            state = None
        if state not in (None, "running", "waiting"):
            return 400, {"message": f"bad state: {state!r} "
                         "(running|waiting|all)"}, None
        prof = scope.get_profiler()
        text = prof.collapsed(
            seconds, state=state, role=qs.get("role", [None])[0] or None
        )
        head = (
            f"# pio-scope folded stacks seconds={seconds:g} "
            f"hz={prof.hz:g} running={int(scope.profiler_running())}\n"
        )
        return 200, (head + text).encode(), "text/plain; charset=utf-8"
    if path == "/debug/profile":
        from ..obs import timeline

        qs = urllib.parse.parse_qs(query)
        try:
            seconds = float(qs.get("seconds", ["2"])[0])
        except ValueError:
            return 400, {"message": f"bad seconds: {qs['seconds'][0]!r}"}, None
        try:
            return 200, timeline.capture_profile(seconds), None
        except timeline.ProfileBusy as e:
            return 409, {"message": str(e)}, None
        except Exception as e:
            return 500, {"message": f"profile capture failed: {e}"}, None
    return 200, render_prometheus().encode(), PROMETHEUS_CTYPE


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Base handler: HTTP/1.1 keep-alive + JSON/body helpers."""

    protocol_version = "HTTP/1.1"
    # the reply is two send() calls (buffered headers, then body);
    # without TCP_NODELAY, Nagle holds the body segment until the
    # client's delayed ACK — measured as a ~40 ms stall on EVERY
    # keep-alive POST (pio-pulse loadgen found it; connection-per-
    # request clients like urllib never hit it, which is why the
    # earlier benches didn't see it)
    disable_nagle_algorithm = True
    server_logger = None  # subclasses set a logging.Logger

    def log_message(self, fmt, *args):
        if self.server_logger is not None:
            self.server_logger.debug(fmt, *args)

    def _serve_metrics(self) -> bool:
        """Answer the common observability mounts — ``GET /metrics``
        (Prometheus exposition), ``GET /debug/xray`` (compiler/device/
        flight-recorder JSON, pio-xray), ``GET /debug/train`` (training
        run progress + manifest history, pio-tower) and ``GET
        /debug/profile`` (blocking on-demand jax.profiler capture,
        pio-pulse) — from the process-wide registry.  Every server's
        ``do_GET`` tries this first, so all four HTTP surfaces expose
        the same set without per-server code.  Returns True when the
        request was handled."""
        u = urllib.parse.urlparse(self.path)
        ans = observability_response(u.path, u.query)
        if ans is None:
            return False
        code, payload, ctype = ans
        self._reply(code, payload, ctype=ctype or "application/json")
        return True

    def _trace_id(self) -> Optional[str]:
        """The request's propagated trace id (``X-PIO-Trace``), if any."""
        tid = self.headers.get(TRACE_HEADER)
        return tid.strip() if tid else None

    def _reply(self, code: int, payload: Any,
               ctype: str = "application/json") -> None:
        body = (
            payload
            if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in getattr(self, "extra_headers", ()):
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""


class HTTPServerBase:
    """Mixin providing the bind/serve/background/stop lifecycle.

    Subclasses implement ``_make_handler()`` and expose ``host``/``port``
    attributes (port 0 -> ephemeral, re-read after bind).  Binding happens
    in the caller's thread so bind errors (port in use) surface as
    exceptions instead of hanging a background thread.
    """

    host: str
    port: int
    _httpd = None  # CappedThreadingHTTPServer | EventLoopHTTPServer

    def _make_handler(self):
        raise NotImplementedError

    bind_retries = 3  # MasterActor retries the spray bind 3x in the reference
    # per-server connection bound + metric label; subclasses override
    # (EngineServer reads them from its ServerConfig)
    max_connections: int = DEFAULT_MAX_CONNECTIONS
    server_name: str = "serving"

    def _build_httpd(self):
        """Construct the bound server object.  Default: the capped
        threading edge.  EngineServer/RouterServer override this to
        return an ``eventloop.EventLoopHTTPServer`` — same
        ``server_address``/``serve_forever``/``shutdown``/
        ``server_close`` surface, one lifecycle here."""
        return CappedThreadingHTTPServer(
            (self.host, self.port), self._make_handler(),
            max_connections=self.max_connections,
            server_name=self.server_name,
        )

    def _bind(self) -> None:
        import errno
        import time

        retries = max(1, self.bind_retries)
        for attempt in range(retries):
            try:
                self._httpd = self._build_httpd()
                break
            except OSError as e:
                # only a busy port is transient (a stale server shutting
                # down); permission/addr errors fail immediately
                if e.errno != errno.EADDRINUSE or attempt + 1 >= retries:
                    raise
                time.sleep(1.0)
        self.port = self._httpd.server_address[1]

    _serving: bool = False

    def serve_forever(self) -> None:
        if self._httpd is None:
            self._bind()
        self._serving = True
        self._httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        self._bind()
        self._serving = True
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        if self._httpd is not None:
            if self._serving:
                # shutdown() handshakes with the serve loop; calling it on
                # a bound-but-never-served server would block forever
                self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._serving = False
