"""Admin REST API (port 7071).

Re-expression of reference `tools/admin/AdminAPI.scala:40-154` +
`admin/CommandClient.scala`: app administration over HTTP.

* ``GET    /``                   -> server info
* ``GET    /cmd/app``            -> list apps
* ``POST   /cmd/app``            -> create app (+default access key)
* ``DELETE /cmd/app/<name>``     -> delete app
* ``DELETE /cmd/app/<name>/data``-> wipe app event data
"""

from __future__ import annotations

import json
import logging
import urllib.parse
from typing import Optional

from ..storage.metadata import AccessKey
from ..storage.registry import Storage
from .http_base import HTTPServerBase, JsonRequestHandler

logger = logging.getLogger(__name__)

__all__ = ["AdminServer"]


class AdminServer(HTTPServerBase):
    server_name = "admin"
    def __init__(self, storage: Storage, host: str = "127.0.0.1",
                 port: int = 7071):
        self.storage = storage
        self.host = host
        self.port = port

    # -- command impls (CommandClient.scala) -------------------------------
    def app_list(self) -> list[dict]:
        md = self.storage.get_metadata()
        return [
            {
                "name": a.name,
                "id": a.id,
                "description": a.description,
                "accessKeys": [k.key for k in md.access_key_get_by_app(a.id)],
            }
            for a in md.app_get_all()
        ]

    def app_new(self, name: str, description: Optional[str]) -> dict:
        md = self.storage.get_metadata()
        if md.app_get_by_name(name):
            raise ValueError(f"App {name} already exists.")
        app = md.app_insert(name, description)
        self.storage.get_event_store().init_channel(app.id)
        key = md.access_key_insert(AccessKey(key="", appid=app.id))
        return {"name": app.name, "id": app.id, "accessKey": key}

    def app_delete(self, name: str) -> None:
        md = self.storage.get_metadata()
        app = md.app_get_by_name(name)
        if app is None:
            raise LookupError(f"App {name} not found.")
        es = self.storage.get_event_store()
        for c in md.channel_get_by_app(app.id):
            es.remove_channel(app.id, c.id)
            md.channel_delete(c.id)
        es.remove_channel(app.id)
        for k in md.access_key_get_by_app(app.id):
            md.access_key_delete(k.key)
        md.app_delete(app.id)

    def app_data_delete(self, name: str) -> None:
        md = self.storage.get_metadata()
        app = md.app_get_by_name(name)
        if app is None:
            raise LookupError(f"App {name} not found.")
        es = self.storage.get_event_store()
        es.remove_channel(app.id)
        es.init_channel(app.id)

    # -- http ---------------------------------------------------------------
    def _make_handler(server: "AdminServer"):
        class Handler(JsonRequestHandler):
            server_logger = logger

            def do_GET(self):
                if self._serve_metrics():
                    return
                path = urllib.parse.urlparse(self.path).path
                if path == "/":
                    self._reply(200, {
                        "status": "alive",
                        "description": "predictionio_tpu admin server",
                    })
                elif path == "/cmd/app":
                    self._reply(200, server.app_list())
                else:
                    self._reply(404, {"message": "not found"})

            def do_POST(self):
                path = urllib.parse.urlparse(self.path).path
                if path != "/cmd/app":
                    self._reply(404, {"message": "not found"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n).decode() or "{}")
                    name = body.get("name")
                    if not name:
                        raise ValueError("field 'name' is required")
                    self._reply(
                        201, server.app_new(name, body.get("description"))
                    )
                except ValueError as e:
                    self._reply(400, {"message": str(e)})
                except Exception as e:
                    logger.exception("admin error")
                    self._reply(500, {"message": str(e)})

            def do_DELETE(self):
                path = urllib.parse.urlparse(self.path).path
                parts = [
                    urllib.parse.unquote(x) for x in path.split("/") if x
                ]
                try:
                    if len(parts) == 3 and parts[:2] == ["cmd", "app"]:
                        server.app_delete(parts[2])
                        self._reply(200, {"message": f"App {parts[2]} deleted."})
                    elif (
                        len(parts) == 4
                        and parts[:2] == ["cmd", "app"]
                        and parts[3] == "data"
                    ):
                        server.app_data_delete(parts[2])
                        self._reply(
                            200, {"message": f"App {parts[2]} data deleted."}
                        )
                    else:
                        self._reply(404, {"message": "not found"})
                except LookupError as e:
                    self._reply(404, {"message": str(e)})
                except Exception as e:
                    logger.exception("admin error")
                    self._reply(500, {"message": str(e)})

        return Handler
