"""Evaluation dashboard (port 9000).

Re-expression of reference `tools/dashboard/Dashboard.scala:30-141`: an HTML
index of completed evaluation instances with drill-down to
``evaluator_results.{txt,html,json}`` per instance, plus CORS headers
(`dashboard/CorsSupport.scala`), plus the pio-obs **live metrics** page
(``/metrics.html``: current registry samples + recent spans — the
operator view next to the evaluation index; machines scrape
``/metrics``).
"""

from __future__ import annotations

import html as _html
import json
import logging
import urllib.parse
from ..obs import get_registry, get_tracer, telemetry_home
from ..storage.registry import Storage
from .http_base import HTTPServerBase, JsonRequestHandler

logger = logging.getLogger(__name__)

__all__ = ["DashboardServer"]


class DashboardServer(HTTPServerBase):
    server_name = "dashboard"
    def __init__(self, storage: Storage, host: str = "127.0.0.1",
                 port: int = 9000):
        self.storage = storage
        self.host = host
        self.port = port

    def index_html(self) -> str:
        md = self.storage.get_metadata()
        rows = []
        for ev in md.evaluation_instance_get_completed():
            rows.append(
                "<tr><td>{id}</td><td>{cls}</td><td>{start}</td>"
                "<td>{end}</td><td>{res}</td>"
                "<td><a href='/engine_instances/{id}/evaluator_results.txt'>txt</a> "
                "<a href='/engine_instances/{id}/evaluator_results.html'>html</a> "
                "<a href='/engine_instances/{id}/evaluator_results.json'>json</a>"
                "</td></tr>".format(
                    id=_html.escape(ev.id),
                    cls=_html.escape(ev.evaluation_class),
                    start=_html.escape(ev.start_time),
                    end=_html.escape(ev.end_time),
                    res=_html.escape(ev.evaluator_results),
                )
            )
        # pio-live row: one recent-events link per app (rowid-cursor
        # backed — see events_html), next to the evaluations table
        app_links = " &middot; ".join(
            f"<a href='/events.html?app={a.id}'>{_html.escape(a.name)}"
            f" (id {a.id})</a>"
            for a in md.app_get_all()
        ) or "(no apps)"
        return (
            "<html><head><title>predictionio_tpu dashboard</title></head>"
            "<body><h1>Completed evaluations</h1>"
            "<table border='1'><tr><th>id</th><th>evaluation</th>"
            "<th>start</th><th>end</th><th>result</th><th>details</th></tr>"
            + "\n".join(rows)
            + "</table>"
            "<p>Recent events (pio-live): " + app_links + "</p>"
            "<p><a href='/metrics.html'>live metrics</a> &middot; "
            "<a href='/xray.html'>x-ray</a> &middot; "
            "<a href='/pulse.html'>pulse</a> &middot; "
            "<a href='/train.html'>training console</a> &middot; "
            "<a href='/tenants.html'>tenants</a> &middot; "
            "<a href='/experiments.html'>experiments</a> &middot; "
            "<a href='/fleet.html'>fleet</a> &middot; "
            "<a href='/prof.html'>flamegraph</a> &middot; "
            "<a href='/metrics'>prometheus exposition</a></p>"
            "</body></html>"
        )

    def events_html(self, app_id: int, channel_id: int = 0,
                    limit: int = 50) -> str:
        """Newest events of an (app, channel), via the event store's
        indexed rowid cursor (`SQLiteEventStore.find_rows_since`
        ``newest_first`` — one B-tree range read) instead of a
        full-table scan + time sort.  Stores without the cursor API
        (memory backend) fall back to the reversed time-ordered
        ``find``."""
        es = self.storage.get_event_store()
        rows = []
        if hasattr(es, "find_since"):
            pairs, _ = es.find_since(
                app_id, channel_id, cursor=0, limit=limit,
                newest_first=True,
            )
        else:
            pairs = [
                (0, e)
                for e in es.find(
                    app_id, channel_id, limit=limit, reversed=True
                )
            ]
        for rowid, e in pairs:
            rows.append(
                "<tr><td>{rid}</td><td>{ev}</td><td>{ent}</td>"
                "<td>{tgt}</td><td>{t}</td></tr>".format(
                    rid=rowid or "-",
                    ev=_html.escape(e.event),
                    ent=_html.escape(
                        f"{e.entity_type}/{e.entity_id}"
                    ),
                    tgt=_html.escape(
                        f"{e.target_entity_type}/{e.target_entity_id}"
                        if e.target_entity_id else "-"
                    ),
                    t=_html.escape(str(e.event_time)),
                )
            )
        return (
            "<html><head><title>recent events</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace;padding:2px 8px}</style></head>"
            f"<body><h1>Recent events — app {app_id}"
            f"{f' channel {channel_id}' if channel_id else ''}</h1>"
            "<table border='1'><tr><th>rowid</th><th>event</th>"
            "<th>entity</th><th>target</th><th>time</th></tr>"
            + "\n".join(rows) + "</table>"
            "<p><a href='/'>back</a></p></body></html>"
        )

    def metrics_html(self) -> str:
        """Operator view of the process-wide registry + recent spans."""
        reg = get_registry()
        rows = []
        for name, label_items, value in reg.collect():
            lbl = ", ".join(f"{k}={v}" for k, v in label_items)
            rows.append(
                "<tr><td>{n}</td><td>{l}</td><td>{v}</td></tr>".format(
                    n=_html.escape(name), l=_html.escape(lbl),
                    v=_html.escape(f"{value:g}"),
                )
            )
        spans = get_tracer().spans(limit=50)
        span_rows = [
            "<tr><td>{n}</td><td>{t}</td><td>{d:.3f}</td></tr>".format(
                n=_html.escape(s.name),
                t=_html.escape(s.trace_id or "-"),
                d=s.duration_s * 1e3,
            )
            for s in reversed(spans)
        ]
        return (
            "<html><head><title>live metrics</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace;padding:2px 8px}</style></head>"
            "<body><h1>Live metrics</h1>"
            "<p>Prometheus exposition at <a href='/metrics'>/metrics"
            "</a> &middot; compiler/device view at "
            "<a href='/xray.html'>/xray.html</a>.</p>"
            "<table border='1'><tr><th>metric</th><th>labels</th>"
            "<th>value</th></tr>" + "\n".join(rows) + "</table>"
            "<h2>Recent spans (newest first)</h2>"
            "<table border='1'><tr><th>span</th><th>trace</th>"
            "<th>ms</th></tr>" + "\n".join(span_rows) + "</table>"
            "</body></html>"
        )

    def xray_html(self) -> str:
        """Operator view of the pio-xray payload: jit entry points,
        the recompile ring (with signature deltas), device memory, and
        the slow-query flight recorder.  Machines read /debug/xray."""
        from ..obs.xray import xray_payload

        p = xray_payload()

        def esc(v) -> str:
            return _html.escape(str(v))

        jit_rows = [
            "<tr><td>{f}</td><td>{c}</td><td>{s}</td><td>{bc}</td>"
            "<td>{t}</td></tr>".format(
                f=esc(fn), c=st["calls"], s=st["signatures"],
                bc=st["backendCompiles"],
                t=f"{st['compileSecondsTotal']:.3f}",
            )
            for fn, st in sorted(p["jit"].items())
        ]
        rec_rows = []
        for e in reversed(p["recompiles"]):
            delta = e.get("delta") or {}
            changed = "; ".join(
                f"{c['arg']}: {c['from']} -> {c['to']}"
                for c in delta.get("changed", [])
            ) or "(first signature)"
            rec_rows.append(
                "<tr><td>{f}</td><td>{k}</td><td>{t}</td>"
                "<td>{d}</td></tr>".format(
                    f=esc(e["fn"]), k=esc(e["kind"]),
                    t=esc(e.get("traceId") or "-"), d=esc(changed),
                )
            )
        dev_rows = [
            "<tr><td>{d}</td><td>{s}</td><td>{v}</td></tr>".format(
                d=esc(s["device"]), s=esc(stat), v=f"{v:,}",
            )
            for s in p["devices"]["samples"]
            for stat, v in sorted(s["stats"].items())
        ]
        flight_rows = [
            "<tr><td>{t}</td><td>{ms:.2f}</td><td>{n}</td></tr>".format(
                t=esc(w["traceId"]), ms=w["durationSec"] * 1e3,
                n=w["spanCount"],
            )
            for w in p["flight"]["worst"]
        ]
        cache = p["compileCache"]
        return (
            "<html><head><title>x-ray</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace;padding:2px 8px}</style></head>"
            "<body><h1>X-ray: compiler &amp; device</h1>"
            "<p>JSON at <a href='/debug/xray'>/debug/xray</a>. "
            "Compilation cache: "
            f"<code>{esc(cache['dir'] or 'disabled')}</code> "
            f"{esc(cache['events'] or '')}</p>"
            "<h2>Instrumented jit entry points</h2>"
            "<table border='1'><tr><th>fn</th><th>calls</th>"
            "<th>signatures</th><th>backend compiles</th>"
            "<th>compile s total</th></tr>"
            + "\n".join(jit_rows) + "</table>"
            "<h2>Recompile ring (newest first)</h2>"
            "<table border='1'><tr><th>fn</th><th>kind</th>"
            "<th>trace</th><th>signature delta</th></tr>"
            + "\n".join(rec_rows) + "</table>"
            "<h2>Device memory</h2>"
            "<table border='1'><tr><th>device</th><th>stat</th>"
            "<th>bytes</th></tr>" + "\n".join(dev_rows) + "</table>"
            "<h2>Flight recorder (slowest requests)</h2>"
            "<table border='1'><tr><th>trace</th><th>ms</th>"
            "<th>spans</th></tr>" + "\n".join(flight_rows) + "</table>"
            "</body></html>"
        )

    def tenants_html(self) -> str:
        """Operator view of the pio-hive layer: per-(app, variant)
        serving outcomes and latency, residency/eviction counters, and
        the online A/B table (impressions / conversions / rate) — the
        same registry families ``/metrics`` exposes, rendered per
        tenant.  (Full registry detail lives on the engine server's
        ``GET /debug/tenants``.)"""
        from ..obs import (
            TENANT_LOADS_TOTAL,
            TENANT_MEMORY_BUDGET,
            TENANT_QUERIES_TOTAL,
            TENANT_QUERY_LATENCY,
            TENANT_RESIDENT_BYTES,
            TENANTS_RESIDENT,
            VARIANT_FEEDBACK_TOTAL,
            VARIANT_RATE,
            VARIANT_REQUESTS_TOTAL,
        )

        def esc(v) -> str:
            return _html.escape(str(v))

        def by_tenant(family, value_of):
            out: dict[tuple, dict] = {}
            for key, child in family.children():
                k = dict(key)
                tenant = (k.get("app", "?"), k.get("variant", "?"))
                out.setdefault(tenant, {}).update(value_of(k, child))
            return out

        tenants: dict[tuple, dict] = {}
        for (app, variant), d in by_tenant(
            TENANT_QUERIES_TOTAL,
            lambda k, c: {f"q_{k.get('status', '?')}": c.value()},
        ).items():
            tenants.setdefault((app, variant), {}).update(d)
        for (app, variant), d in by_tenant(
            TENANT_RESIDENT_BYTES,
            lambda k, c: {"resident": c.value()},
        ).items():
            tenants.setdefault((app, variant), {}).update(d)
        for key, child in TENANT_QUERY_LATENCY.children():
            k = dict(key)
            snap = child.snapshot()
            if snap["count"]:
                tenants.setdefault(
                    (k.get("app", "?"), k.get("variant", "?")), {}
                ).update({
                    "p50_ms": child.percentile(50, snap) * 1e3,
                    "p95_ms": child.percentile(95, snap) * 1e3,
                })
        rows = []
        for (app, variant) in sorted(tenants):
            d = tenants[(app, variant)]
            rows.append(
                "<tr><td>{a}/{v}</td><td>{r}</td><td>{ok:g}</td>"
                "<td>{err:g}</td><td>{shed:g}</td><td>{q:g}</td>"
                "<td>{p50:.2f} / {p95:.2f}</td></tr>".format(
                    a=esc(app), v=esc(variant),
                    r=("%.1f KB" % (d["resident"] / 1e3)
                       if d.get("resident") else "—"),
                    ok=d.get("q_ok", 0.0), err=d.get("q_error", 0.0),
                    shed=d.get("q_shed", 0.0) + d.get("q_rejected", 0.0),
                    q=d.get("q_quota", 0.0),
                    p50=d.get("p50_ms", 0.0), p95=d.get("p95_ms", 0.0),
                )
            )
        ab: dict[tuple, dict] = {}
        for fam, field in ((VARIANT_REQUESTS_TOTAL, "impressions"),
                           (VARIANT_FEEDBACK_TOTAL, "conversions"),
                           (VARIANT_RATE, "rate")):
            for key, child in fam.children():
                k = dict(key)
                ab.setdefault(
                    (k.get("app", "?"), k.get("variant", "?")), {}
                )[field] = child.value()
        ab_rows = [
            "<tr><td>{a}/{v}</td><td>{i:g}</td><td>{c:g}</td>"
            "<td>{r:.4f}</td></tr>".format(
                a=esc(app), v=esc(variant),
                i=d.get("impressions", 0.0),
                c=d.get("conversions", 0.0),
                r=d.get("rate", 0.0),
            )
            for (app, variant), d in sorted(ab.items())
        ]
        loads = {"load": 0.0, "evict": 0.0, "overcommit": 0.0}
        for key, child in TENANT_LOADS_TOTAL.children():
            kind = dict(key).get("kind", "?")
            loads[kind] = loads.get(kind, 0.0) + child.value()
        budget = TENANT_MEMORY_BUDGET.child().value()
        head = (
            "<p>resident tenants: <b>{:g}</b> &middot; memory budget: "
            "<b>{}</b> &middot; loads {:g} / evictions {:g} / "
            "overcommits {:g}</p>".format(
                TENANTS_RESIDENT.child().value(),
                ("%.1f MB" % (budget / 1e6)) if budget else "unbounded",
                loads["load"], loads["evict"], loads["overcommit"],
            )
        )
        return (
            "<!DOCTYPE html><html><head><title>pio-hive tenants</title>"
            "<meta http-equiv='refresh' content='5'>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td,th{padding:3px 8px;font-family:monospace}</style>"
            "</head><body><h1>Tenants (pio-hive)</h1>" + head +
            "<h2>Per-tenant serving</h2>"
            "<table border='1'><tr><th>tenant</th><th>resident</th>"
            "<th>ok</th><th>errors</th><th>shed</th><th>quota 429s</th>"
            "<th>p50 / p95 ms</th></tr>" + "".join(rows) + "</table>"
            "<h2>Online A/B (per variant)</h2>"
            "<table border='1'><tr><th>variant</th><th>impressions</th>"
            "<th>conversions</th><th>rate</th></tr>" +
            "".join(ab_rows) + "</table>"
            "<p><a href='/'>index</a></p></body></html>"
        )

    def experiments_html(self, server_url: str = "") -> str:
        """pio-pilot experiment console: per-app SPRT state (LLR walk
        vs its thresholds), live weights, guardrail vetoes, and the
        ramp-decision tail.  Renders the in-process autopilot when one
        exists, else fetches ``?server=http://host:port``'s
        ``/debug/experiments``, else falls back to the newest
        ``pilot-*`` tower manifest on disk (cross-process view)."""
        from ..tenancy.autopilot import autopilot_payload

        def esc(v) -> str:
            return _html.escape(str(v))

        p = autopilot_payload()
        source = "in-process autopilot"
        if p is None and server_url:
            import urllib.request
            try:
                with urllib.request.urlopen(
                    server_url.rstrip("/") + "/debug/experiments",
                    timeout=5,
                ) as r:
                    p = json.loads(r.read().decode())
                source = esc(server_url)
            except Exception as e:
                return (
                    "<html><body><h1>Experiments</h1><p>could not "
                    f"reach {esc(server_url)}/debug/experiments: "
                    f"{esc(e)}</p></body></html>"
                )
        if p is None:
            p = self._experiments_from_manifest()
            source = "tower manifest"
        if p is None:
            return (
                "<html><body><h1>Experiments</h1><p>No autopilot in "
                "this process and no pilot manifest on disk. Point me "
                "at a serving edge with <code>/experiments.html?"
                "server=http://host:port</code> or curl its "
                "<code>/debug/experiments</code>.</p></body></html>"
            )
        app_rows = []
        for app, cell in sorted((p.get("apps") or {}).items()):
            last = cell.get("last") or {}
            llr = last.get("llr")
            walk = (
                f"{llr:.3f} in [{last.get('lower', 0):.3f}, "
                f"{last.get('upper', 0):.3f}]"
                if llr is not None else "-"
            )
            weights = ", ".join(
                f"{v}={w:.3f}" for v, w in sorted(
                    (p.get("weights", {}).get(app) or
                     last.get("weights") or {}).items()
                )
            )
            vetoes = ", ".join(
                f"{v}:{r}" for v, r in
                sorted((last.get("vetoes") or {}).items())
            ) or "-"
            app_rows.append(
                "<tr><td>{a}</td><td>{st}</td><td>{d}</td>"
                "<td>{lead}</td><td>{walk}</td><td>{w}</td>"
                "<td>{veto}</td></tr>".format(
                    a=esc(app), st=esc(cell.get("stateName", "?")),
                    d=esc(last.get("decision", "-")),
                    lead=esc(last.get("leader") or
                             last.get("target") or "-"),
                    walk=esc(walk), w=esc(weights), veto=esc(vetoes),
                )
            )
        dec_rows = []
        for app, cell in sorted((p.get("apps") or {}).items()):
            for d in reversed(cell.get("decisions") or []):
                dec_rows.append(
                    "<tr><td>{a}</td><td>{dec}</td><td>{r}</td>"
                    "<td>{llr}</td><td>{w}</td></tr>".format(
                        a=esc(app), dec=esc(d.get("decision")),
                        r=esc(d.get("reason") or "-"),
                        llr=(f"{d['llr']:.3f}"
                             if d.get("llr") is not None else "-"),
                        w=esc(", ".join(
                            f"{v}={w:.3f}" for v, w in
                            sorted((d.get("weights") or {}).items())
                        )),
                    )
                )
        cfg = p.get("config") or {}
        cfg_html = " &middot; ".join(
            f"{k}={cfg[k]}" for k in sorted(cfg)
        )
        return (
            "<!DOCTYPE html><html><head><title>experiments</title>"
            "<meta http-equiv='refresh' content='5'>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td,th{padding:3px 8px;font-family:monospace}</style>"
            "</head><body><h1>Experiments (pio-pilot)</h1>"
            f"<p>source: {source} &middot; manifest "
            f"<code>{esc(p.get('manifestId', '?'))}</code> &middot; "
            f"ticks {p.get('ticks', '?')}</p>"
            f"<p>{cfg_html}</p>"
            "<h2>Per-app SPRT state</h2>"
            "<table border='1'><tr><th>app</th><th>state</th>"
            "<th>last decision</th><th>leader</th>"
            "<th>LLR walk</th><th>weights</th><th>vetoes</th></tr>"
            + "\n".join(app_rows) + "</table>"
            "<h2>Decision tail (newest first)</h2>"
            "<table border='1'><tr><th>app</th><th>decision</th>"
            "<th>reason</th><th>LLR</th><th>weights</th></tr>"
            + "\n".join(dec_rows) + "</table>"
            "<p>JSON at the serving edge's "
            "<code>/debug/experiments</code>; every decision is a "
            "pio-tower manifest event (<code>tools/runlog.py</code>)."
            "</p><p><a href='/'>index</a></p></body></html>"
        )

    def _experiments_from_manifest(self):
        """Newest ``pilot-*`` run manifest rebuilt into (a subset of)
        the autopilot payload shape — the cross-process fallback."""
        from ..obs.runlog import read_manifest, runs_root

        try:
            dirs = sorted(
                (d for d in runs_root().iterdir()
                 if d.name.startswith("pilot-")),
                key=lambda d: d.stat().st_mtime, reverse=True,
            )
        except OSError:
            return None
        for d in dirs:
            doc = read_manifest(d)
            if doc is None:
                continue
            apps: dict[str, dict] = {}
            for ev in doc.get("events", ()):
                if ev.get("event") != "decision":
                    continue
                app = ev.get("app", "?")
                cell = apps.setdefault(
                    app, {"stateName": "?", "decisions": []}
                )
                cell["last"] = ev
                cell["decisions"].append(ev)
                del cell["decisions"][:-10]
                state = ev.get("state")
                cell["stateName"] = {
                    0.0: "collecting", 1.0: "ramping",
                    2.0: "concluded", 3.0: "frozen",
                }.get(state, "?")
            header = doc.get("header") or {}
            return {
                "enabled": True,
                "manifestId": header.get("instanceId", d.name),
                "ticks": len(doc.get("events", ())),
                "config": {
                    k: header[k]
                    for k in ("alpha", "beta", "minLift", "minSamples",
                              "maxStep", "minWeight")
                    if k in header
                },
                "weights": {},
                "apps": apps,
            }
        return None

    def pulse_html(self) -> str:
        """Operator view of the pio-pulse request-lifecycle layer: the
        per-segment decomposition of serving and ingest latency, the
        micro-batcher's concurrency saturation counters, and the
        latest closed-loop sweep (``bench_serving.py --sweep`` writes
        ``telemetry/sweeps/latest.json``)."""
        from ..obs.timeline import (
            EVENT_SEGMENTS,
            EVENTS_SEGMENT_SECONDS,
            MICROBATCH_BATCH_SIZE,
            MICROBATCH_QUEUE_DEPTH,
            MICROBATCH_ROLE_TOTAL,
            SERVE_INFLIGHT,
            SERVE_SEGMENTS,
            SERVE_SEGMENT_SECONDS,
        )

        def esc(v) -> str:
            return _html.escape(str(v))

        def seg_rows(family, segments):
            rows = []
            for s in segments:
                child = family.labels(segment=s)
                snap = child.snapshot()
                n = snap["count"]
                mean = (snap["sum"] / n * 1e3) if n else 0.0
                p95 = child.percentile(95, snap) * 1e3 if n else 0.0
                rows.append(
                    "<tr><td>{s}</td><td>{n}</td><td>{m:.3f}</td>"
                    "<td>{p:.3f}</td></tr>".format(
                        s=esc(s), n=n, m=mean, p=p95,
                    )
                )
            return rows

        seg_table = (
            "<table border='1'><tr><th>segment</th><th>count</th>"
            "<th>mean ms</th><th>p95 ms</th></tr>"
        )
        bs = MICROBATCH_BATCH_SIZE.child()
        bs_snap = bs.snapshot()
        roles = {
            dict(key).get("role", "?"): child.value()
            for key, child in MICROBATCH_ROLE_TOTAL.children()
        }
        sat_rows = [
            "<tr><td>inflight</td><td>{:g}</td></tr>".format(
                SERVE_INFLIGHT.child().value()),
            "<tr><td>batcher queue depth</td><td>{:g}</td></tr>".format(
                MICROBATCH_QUEUE_DEPTH.child().value()),
            "<tr><td>batches dispatched</td><td>{}</td></tr>".format(
                bs_snap["count"]),
            "<tr><td>mean batch size</td><td>{:.2f}</td></tr>".format(
                bs_snap["sum"] / bs_snap["count"]
                if bs_snap["count"] else 0.0),
            "<tr><td>leader / follower requests</td>"
            "<td>{:g} / {:g}</td></tr>".format(
                roles.get("leader", 0.0), roles.get("follower", 0.0)),
        ]
        sweep_html = "<p>(no sweep recorded yet — run "
        sweep_html += "<code>bench_serving.py --sweep 1,4,16</code>)</p>"
        sweep_path = telemetry_home() / "sweeps" / "latest.json"
        try:
            sweep = json.loads(sweep_path.read_text())
        except (OSError, json.JSONDecodeError):
            sweep = None
        if sweep:
            rows = []
            for p in sweep.get("points", ()):
                segs = "; ".join(
                    f"{k} {v:.2f}" for k, v in
                    sorted(p.get("segments_ms", {}).items(),
                           key=lambda kv: -kv[1])[:4]
                )
                rows.append(
                    "<tr><td>{c}</td><td>{q:.1f}</td><td>{p50:.2f}</td>"
                    "<td>{p99:.2f}</td><td>{e}</td><td>{s}</td>"
                    "</tr>".format(
                        c=p.get("concurrency"), q=p.get("qps", 0.0),
                        p50=p.get("p50_ms", 0.0),
                        p99=p.get("p99_ms", 0.0),
                        e=p.get("errors", 0), s=esc(segs),
                    )
                )
            slo = sweep.get("slo_ms")
            qps = sweep.get("qps_at_slo")
            sweep_html = (
                "<p>recorded {at} on {plat}; QPS@SLO(p99 &le; "
                "{slo} ms) = <b>{qps}</b></p>"
                "<table border='1'><tr><th>concurrency</th><th>qps</th>"
                "<th>p50 ms</th><th>p99 ms</th><th>errors</th>"
                "<th>top segments (mean ms)</th></tr>".format(
                    at=esc(sweep.get("recorded_at", "?")),
                    plat=esc(sweep.get("platform", "?")),
                    slo=esc(slo), qps=esc(qps if qps is not None
                                          else "(no point met SLO)"),
                ) + "\n".join(rows) + "</table>"
            )
        return (
            "<html><head><title>pulse</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace;padding:2px 8px}</style></head>"
            "<body><h1>Pulse: request lifecycle &amp; saturation</h1>"
            "<p>Segment histograms at <a href='/metrics'>/metrics</a> "
            "(pio_serve_segment_seconds / pio_events_segment_seconds); "
            "on-demand profiler at <code>/debug/profile?seconds=S</code> "
            "on any server.</p>"
            "<h2>Serving segments</h2>"
            + seg_table
            + "\n".join(seg_rows(SERVE_SEGMENT_SECONDS, SERVE_SEGMENTS))
            + "</table>"
            "<h2>Event-ingest segments</h2>"
            + seg_table
            + "\n".join(seg_rows(EVENTS_SEGMENT_SECONDS, EVENT_SEGMENTS))
            + "</table>"
            "<h2>Concurrency saturation</h2>"
            "<table border='1'><tr><th>gauge</th><th>value</th></tr>"
            + "\n".join(sat_rows) + "</table>"
            "<h2>Latest closed-loop sweep</h2>" + sweep_html +
            "</body></html>"
        )

    def prof_html(self, target_url: str = "", seconds: float = 60.0,
                  state: str = "", baseline_url: str = "") -> str:
        """pio-scope flamegraph console: render any hive process's
        rolling CPU profile as a zoomable flamegraph — no external
        assets, no tooling on the operator's box.  ``?target=http://
        host:port`` fetches that server's ``/debug/pprof`` (router,
        replica, eventserver, ingest router — the mount is universal);
        no target renders THIS dashboard process's own ring.
        ``&baseline=URL`` overlays a second profile as share deltas
        (the profcat A/B diff, served live)."""
        from ..obs import scope

        def fetch(url: str) -> str:
            import urllib.request
            qs = f"/debug/pprof?seconds={seconds:g}"
            if state:
                qs += f"&state={urllib.parse.quote(state)}"
            with urllib.request.urlopen(
                url.rstrip("/") + qs, timeout=5
            ) as r:
                return r.read().decode()

        try:
            if target_url:
                folded = fetch(target_url)
                title = f"pio-scope: {target_url} (last {seconds:g}s)"
            else:
                folded = scope.get_profiler().collapsed(
                    seconds, state=state or None
                )
                title = f"pio-scope: dashboard process (last {seconds:g}s)"
            baseline = fetch(baseline_url) if baseline_url else None
        except Exception as e:
            esc = _html.escape
            return (
                "<html><body><h1>Profile</h1><p>could not fetch "
                f"profile: {esc(str(e))}</p><p>Usage: <code>"
                "/prof.html?target=http://host:port&amp;seconds=60"
                "&amp;state=running&amp;baseline=http://other:port"
                "</code></p></body></html>"
            )
        return scope.flamegraph_html(folded, title=title,
                                     baseline=baseline)

    def fleet_html(self, router_url: str = "") -> str:
        """pio-lens fleet console: the per-replica tail table (p50/p99
        off each replica's scraped latency histogram, breaker/respawn/
        scrape state) and the router flight recorder's worst-N with
        per-replica attribution.  Renders the in-process router's
        payload when one exists (``deploy --replicas`` runs the router
        in this process in fleet mode tests), else fetches
        ``?router=http://host:port``'s ``/debug/fleet``.  Machines
        read ``/debug/fleet`` on the router."""
        from ..obs import fleet

        def esc(v) -> str:
            return _html.escape(str(v))

        p = fleet.fleet_payload()
        source = "in-process router"
        if p is None and router_url:
            import urllib.request
            try:
                with urllib.request.urlopen(
                    router_url.rstrip("/") + "/debug/fleet", timeout=5
                ) as r:
                    p = json.loads(r.read().decode())
                source = esc(router_url)
            except Exception as e:
                return (
                    "<html><body><h1>Fleet</h1><p>could not reach "
                    f"{esc(router_url)}/debug/fleet: {esc(e)}</p>"
                    "</body></html>"
                )
        if p is None:
            return (
                "<html><body><h1>Fleet</h1><p>No router in this "
                "process. Point me at one with "
                "<code>/fleet.html?router=http://host:port</code> or "
                "curl the router's <code>/debug/fleet</code>.</p>"
                "</body></html>"
            )
        rows = []
        for r in p.get("replicas", ()):
            rows.append(
                "<tr><td>{n}</td><td>{h}</td><td>{b}</td>"
                "<td>{p50}</td><td>{p99}</td><td>{q:g}</td>"
                "<td>{f}</td><td>{rsp:g}</td><td>{se}</td></tr>".format(
                    n=esc(r.get("name")),
                    h="up" if r.get("healthy") else "<b>DOWN</b>",
                    b=esc(r.get("breaker", "?")),
                    p50=r.get("p50Ms", "-"), p99=r.get("p99Ms", "-"),
                    q=r.get("queriesTotal", 0.0),
                    f=r.get("failovers", 0),
                    rsp=r.get("respawns", 0.0),
                    se=r.get("scrapeErrors", 0),
                )
            )
        worst_rows = []
        for w in p.get("worst", ()):
            attrs = w.get("attrs") or {}
            segs = "; ".join(
                f"{k} {v}" for k, v in sorted(
                    (attrs.get("segmentsMs") or {}).items(),
                    key=lambda kv: -kv[1])[:4]
            )
            rsegs = "; ".join(
                f"{k} {v}" for k, v in sorted(
                    (attrs.get("replicaSegmentsMs") or {}).items(),
                    key=lambda kv: -kv[1])[:4]
            ) or "-"
            worst_rows.append(
                "<tr><td>{t}</td><td>{ms:.1f}</td><td>{r}</td>"
                "<td>{est}</td><td>{segs}</td><td>{rsegs}</td>"
                "</tr>".format(
                    t=esc(w.get("traceId")),
                    ms=w.get("durationSec", 0.0) * 1e3,
                    r=esc(attrs.get("replica", "?")),
                    est=attrs.get("ewmaAtAdmissionSec", "-"),
                    segs=esc(segs) or "-", rsegs=esc(rsegs),
                )
            )
        burn = p.get("burnRate") or {}
        burn_html = ""
        if burn:
            burn_html = (
                "<p>SLO {slo} ms — burn rate "
                + " &middot; ".join(
                    f"{w}: <b>{burn[w]}</b>" for w in sorted(burn)
                ) + "</p>"
            ).format(slo=esc(p.get("sloMs")))
        return (
            "<html><head><title>fleet</title>"
            "<meta http-equiv='refresh' content='5'>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace;padding:2px 8px}</style></head>"
            "<body><h1>Fleet (pio-lens)</h1>"
            f"<p>source: {source} &middot; healthy "
            f"{p.get('healthyReplicas')}/{len(p.get('replicas', ()))} "
            "&middot; EWMA forward "
            f"{p.get('ewmaForwardSec', 0.0) * 1e3:.2f} ms &middot; "
            f"unroutable {p.get('unroutable', 0)} &middot; "
            f"scrape errors {p.get('scrapeErrors', 0)}</p>"
            + burn_html +
            "<h2>Per-replica tail</h2>"
            "<table border='1'><tr><th>replica</th><th>health</th>"
            "<th>breaker</th><th>p50 ms</th><th>p99 ms</th>"
            "<th>queries</th><th>failovers</th><th>respawns</th>"
            "<th>scrape errs</th></tr>" + "\n".join(rows) + "</table>"
            "<h2>Worst requests (router flight recorder)</h2>"
            "<table border='1'><tr><th>trace</th><th>ms</th>"
            "<th>replica</th><th>EWMA@admit s</th>"
            "<th>router segments ms</th><th>replica segments ms</th>"
            "</tr>" + "\n".join(worst_rows) + "</table>"
            "<p>Stitch one trace across processes: "
            "<code>python tools/tracecat.py &lt;trace-id&gt;</code>. "
            "JSON at the router's <code>/debug/fleet</code>; merged "
            "exposition at its <code>/metrics</code>.</p>"
            "<p><a href='/'>index</a></p></body></html>"
        )

    def train_html(self) -> str:
        """pio-tower training console: the live run (if any — this
        process, or another process's manifest still growing on disk)
        plus manifest history with phase totals and loss trajectory
        endpoints.  Machines read ``/debug/train``; ``tools/runlog.py
        diff`` answers "why did sweep 7 take 3x" from the same files."""
        from ..obs.tower import train_payload

        def esc(v) -> str:
            return _html.escape(str(v))

        p = train_payload()
        active = p["active"]
        if active:
            last = active.get("lastSweep") or {}
            seg = "; ".join(
                f"{k} {v * 1e3:.1f}ms"
                for k, v in sorted((last.get("phases") or {}).items())
            )
            planned = active.get("sweepsPlanned")
            eta = active.get("etaSeconds")
            active_html = (
                "<p><b>live:</b> {iid} ({kind}) — sweep {i}{of}, "
                "last {ls:.3f}s [{seg}], ETA {eta}</p>".format(
                    iid=esc(active["instanceId"]),
                    kind=esc(active["runKind"]),
                    i=active["sweep"],
                    of=f"/{planned}" if planned else "",
                    ls=(last.get("seconds") or 0.0),
                    seg=esc(seg),
                    eta=f"{eta:.0f}s" if eta is not None else "?",
                )
            )
        else:
            active_html = "<p>(no run live in this process)</p>"
        rows = []
        for r in p["runs"]:
            phases = "; ".join(
                f"{k} {v:.2f}s" for k, v in sorted(
                    (r.get("phaseTotals") or {}).items(),
                    key=lambda kv: -kv[1],
                )[:4]
            )
            loss = (
                f"{r['firstLoss']:.4g} &rarr; {r['lastLoss']:.4g}"
                if r.get("firstLoss") is not None
                and r.get("lastLoss") is not None else "-"
            )
            status = r.get("status", "?")
            if r.get("live"):
                status = "<b>live</b>"
            elif r.get("reason"):
                status += f" ({esc(r['reason'])})"
            rows.append(
                "<tr><td>{iid}</td><td>{kind}</td><td>{st}</td>"
                "<td>{n}{of}</td><td>{mean}</td><td>{ph}</td>"
                "<td>{loss}</td><td>{ev}</td></tr>".format(
                    iid=esc(r.get("instanceId")),
                    kind=esc(r.get("runKind")),
                    st=status,
                    n=r.get("sweeps"),
                    of=(
                        f"/{r['sweepsPlanned']}"
                        if r.get("sweepsPlanned") else ""
                    ),
                    mean=(
                        f"{r['sweepSecondsMean']:.3f}s"
                        if r.get("sweepSecondsMean") is not None else "-"
                    ),
                    ph=esc(phases) or "-",
                    loss=loss,
                    ev=r.get("events", 0),
                )
            )
        return (
            "<html><head><title>training console</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace;padding:2px 8px}</style></head>"
            "<body><h1>Tower: training console</h1>"
            "<p>JSON at <a href='/debug/train'>/debug/train</a>; "
            "compare two runs with <code>python tools/runlog.py diff "
            "A B</code>.</p>"
            + active_html +
            "<h2>Run manifests (newest first)</h2>"
            "<table border='1'><tr><th>instance</th><th>kind</th>"
            "<th>status</th><th>sweeps</th><th>mean sweep</th>"
            "<th>top phases (total)</th><th>loss first&rarr;last</th>"
            "<th>events</th></tr>" + "\n".join(rows) + "</table>"
            "</body></html>"
        )

    def _make_handler(server: "DashboardServer"):
        class Handler(JsonRequestHandler):
            server_logger = logger
            # CORS (reference CorsSupport.scala)
            extra_headers = (("Access-Control-Allow-Origin", "*"),)

            def do_GET(self):
                if self._serve_metrics():
                    return
                path = urllib.parse.urlparse(self.path).path
                if path == "/":
                    self._reply(200, server.index_html().encode(), "text/html")
                    return
                if path == "/metrics.html":
                    self._reply(200, server.metrics_html().encode(),
                                "text/html")
                    return
                if path == "/events.html":
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query
                    )
                    try:
                        app_id = int(q.get("app", ["-1"])[0])
                        channel = int(q.get("channel", ["0"])[0])
                        limit = min(int(q.get("n", ["50"])[0]), 500)
                    except ValueError:
                        self._reply(400, b"bad query", "text/plain")
                        return
                    self._reply(
                        200,
                        server.events_html(app_id, channel, limit).encode(),
                        "text/html",
                    )
                    return
                if path == "/xray.html":
                    self._reply(200, server.xray_html().encode(),
                                "text/html")
                    return
                if path == "/pulse.html":
                    self._reply(200, server.pulse_html().encode(),
                                "text/html")
                    return
                if path == "/train.html":
                    self._reply(200, server.train_html().encode(),
                                "text/html")
                    return
                if path == "/tenants.html":
                    self._reply(200, server.tenants_html().encode(),
                                "text/html")
                    return
                if path == "/experiments.html":
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query
                    )
                    self._reply(
                        200,
                        server.experiments_html(
                            q.get("server", [""])[0]
                        ).encode(),
                        "text/html",
                    )
                    return
                if path == "/fleet.html":
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query
                    )
                    self._reply(
                        200,
                        server.fleet_html(
                            q.get("router", [""])[0]
                        ).encode(),
                        "text/html",
                    )
                    return
                if path == "/prof.html":
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query
                    )
                    try:
                        seconds = float(q.get("seconds", ["60"])[0])
                    except ValueError:
                        seconds = 60.0
                    self._reply(
                        200,
                        server.prof_html(
                            q.get("target", [""])[0],
                            seconds=seconds,
                            state=q.get("state", [""])[0],
                            baseline_url=q.get("baseline", [""])[0],
                        ).encode(),
                        "text/html",
                    )
                    return
                parts = [x for x in path.split("/") if x]
                if len(parts) == 2 and parts[0] == "engine_instances":
                    # also accept bare ids -> json
                    parts = [parts[0], parts[1], "evaluator_results.json"]
                if len(parts) == 3 and parts[0] == "engine_instances":
                    ev = server.storage.get_metadata().evaluation_instance_get(
                        parts[1]
                    )
                    if ev is None:
                        self._reply(404, b"not found", "text/plain")
                        return
                    which = parts[2]
                    if which == "evaluator_results.txt":
                        self._reply(200, ev.evaluator_results.encode(),
                                    "text/plain")
                    elif which == "evaluator_results.html":
                        self._reply(200, ev.evaluator_results_html.encode(),
                                    "text/html")
                    elif which == "evaluator_results.json":
                        self._reply(200, ev.evaluator_results_json.encode(),
                                    "application/json")
                    else:
                        self._reply(404, b"not found", "text/plain")
                else:
                    self._reply(404, b"not found", "text/plain")

        return Handler
