"""Event-server stats: lifetime + hourly counters
(reference `data/api/StatsActor.scala:29-74`, `data/api/Stats.scala:27-79`).

Counters by (appId, status-code) and (appId, event, entityType,
targetEntityType); the actor model collapses to a lock-guarded aggregate fed
fire-and-forget from the request handlers.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Optional

from ..obs import EVENTS_TOTAL, RESILIENCE_TOTAL

__all__ = ["Stats", "StatsCollector", "KindedEvent",
           "merge_stats_payloads"]


def _merge_stats_json(parts: list[dict]) -> dict:
    counts: Counter = Counter()
    kinds: Counter = Counter()
    start = None
    for p in parts:
        st = p.get("startTime")
        if st is not None:
            start = st if start is None else min(start, st)
        for row in p.get("statusCount", ()):
            counts[(row["appId"], row["status"])] += row["count"]
        for row in p.get("eventCount", ()):
            key = (row["appId"], row["event"], row["entityType"],
                   row.get("targetEntityType"))
            kinds[key] += row["count"]
    return {
        "startTime": start if start is not None else time.time(),
        "statusCount": [
            {"appId": a, "status": s, "count": c}
            for (a, s), c in sorted(counts.items())
        ],
        "eventCount": [
            {"appId": a, "event": e, "entityType": et,
             "targetEntityType": tet, "count": c}
            for (a, e, et, tet), c in sorted(
                kinds.items(), key=lambda kv: (kv[0][0], kv[0][1])
            )
        ],
    }


def merge_stats_payloads(payloads: list[dict]) -> dict:
    """Federate per-worker ``/stats.json`` payloads into one fleet
    view (pio-levee satellite): counters sum by key, ``startTime`` is
    the fleet's earliest boot.  Same monotone-through-death discipline
    as the ``/metrics`` federation — feed a dead worker's LAST GOOD
    payload and the merged counts never step backward; they resume
    climbing when its replacement reports in (counts restart at zero
    per process, so the merged total dips only if the caller DROPS the
    dead worker's snapshot instead of keeping it standing)."""
    out: dict = {}
    for window in ("lifetime", "currentHour"):
        out[window] = _merge_stats_json(
            [p.get(window) or {} for p in payloads]
        )
    prevs = [p["previousHour"] for p in payloads
             if p.get("previousHour")]
    out["previousHour"] = _merge_stats_json(prevs) if prevs else None
    res: Counter = Counter()
    for p in payloads:
        for k, v in (p.get("resilience") or {}).items():
            res[k] += v
    out["resilience"] = dict(sorted(res.items()))
    return out


@dataclass(frozen=True)
class KindedEvent:
    app_id: int
    event: str
    entity_type: str
    target_entity_type: Optional[str]


@dataclass
class Stats:
    start_time: float = field(default_factory=time.time)
    status_count: Counter = field(default_factory=Counter)  # (appId, status)
    event_count: Counter = field(default_factory=Counter)   # KindedEvent

    def update(self, app_id: int, status: int, kinded: Optional[KindedEvent]):
        self.status_count[(app_id, status)] += 1
        if kinded is not None:
            self.event_count[kinded] += 1

    def to_json(self, app_id: Optional[int] = None) -> dict:
        def keep_app(a):
            return app_id is None or a == app_id

        return {
            "startTime": self.start_time,
            "statusCount": [
                {"appId": a, "status": s, "count": c}
                for (a, s), c in sorted(self.status_count.items())
                if keep_app(a)
            ],
            "eventCount": [
                {
                    "appId": k.app_id,
                    "event": k.event,
                    "entityType": k.entity_type,
                    "targetEntityType": k.target_entity_type,
                    "count": c,
                }
                for k, c in sorted(
                    self.event_count.items(),
                    key=lambda kv: (kv[0].app_id, kv[0].event),
                )
                if keep_app(k.app_id)
            ],
        }


class StatsCollector:
    """Long-lived + current-hour + previous-hour windows
    (reference `StatsActor`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.lifetime = Stats()
        self.current = Stats()
        self.previous: Optional[Stats] = None
        self._hour = self._hour_now()
        # resilience counters (storage.write.retry, storage.read.retry,
        # ...): lifetime-scoped, fed by the retry policies' on_retry
        # hooks so operators can see recovered-from trouble, not just
        # terminal failures
        self.resilience: Counter = Counter()

    @staticmethod
    def _hour_now() -> int:
        return int(time.time() // 3600)

    def _roll(self) -> None:
        h = self._hour_now()
        if h != self._hour:
            self.previous = self.current
            self.current = Stats()
            self._hour = h

    def bookkeeping(self, app_id: int, status: int, event=None) -> None:
        kinded = (
            KindedEvent(
                app_id=app_id,
                event=event.event,
                entity_type=event.entity_type,
                target_entity_type=event.target_entity_type,
            )
            if event is not None
            else None
        )
        with self._lock:
            self._roll()
            self.lifetime.update(app_id, status, kinded)
            self.current.update(app_id, status, kinded)
        # mirror into the process-wide registry (pio-obs): same counts,
        # scrape-able as pio_events_requests_total{status=...} without
        # the /stats.json auth round-trip.  Status alone keeps the
        # label cardinality bounded; per-app drill-down stays in
        # /stats.json where it always lived.
        EVENTS_TOTAL.labels(status=str(status)).inc()

    def note(self, counter: str, n: int = 1) -> None:
        """Bump a named resilience counter (e.g. ``storage.write.retry``)."""
        with self._lock:
            self.resilience[counter] += n
        RESILIENCE_TOTAL.labels(kind=counter).inc(n)

    def to_json(self, app_id: Optional[int] = None) -> dict:
        with self._lock:
            self._roll()
            return {
                "lifetime": self.lifetime.to_json(app_id),
                "currentHour": self.current.to_json(app_id),
                "previousHour": (
                    self.previous.to_json(app_id) if self.previous else None
                ),
                "resilience": dict(sorted(self.resilience.items())),
            }
