"""pio-surge: serving replica fleet router.

One serving process is one core's worth of QPS; "millions of users"
means going horizontal.  ``pio-tpu deploy --replicas N`` boots N
single-replica EngineServer *processes* (each its own interpreter —
no shared GIL, its own device queue, its own ``/metrics``) and ONE
router process in front:

* **Routing**: ``POST /queries.json`` round-robins over healthy
  replicas on pooled keep-alive connections.  A transport failure
  (replica killed, connection refused, read timeout) marks the replica
  down, books a ``failover``, and retries the SAME request on the next
  replica — predicts are idempotent, so the client sees one 200 and no
  evidence a replica died.  Only when every replica is unreachable
  does the router answer a structured 503.
* **Health**: a daemon thread polls each replica's ``GET /`` status
  every ``health_interval_s``, maintaining per-replica health, breaker
  state, and the fleet gauges ``pio_replica_up{replica}`` /
  ``pio_replica_model_freshness_seconds{replica}`` (the labeled
  fleet-wide view of each replica's own
  ``pio_model_freshness_seconds``).
* **Rolling delta push (pio-live x fleet)**: ``POST
  /admin/push-foldin`` walks the replicas ONE AT A TIME, POSTing
  ``/foldin/apply`` so each patches any pending fold-in delta links in
  place (no reload, no warmup).  Strictly sequential by construction:
  fleet availability never drops below N-1 replicas during a push, and
  a replica that fails to apply keeps serving its stale model while
  the rest of the fleet advances.  ``--push-foldin SEC`` runs the same
  rolling push on a timer.

The router itself rides the event-loop edge (`server/eventloop.py`):
the loop parses and routes, a bounded worker pool does the blocking
upstream HTTP, so router threads are O(pool), not O(connections).
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import subprocess
import sys
import threading
import time
import urllib.parse
from pathlib import Path
from typing import Optional

from ..obs import (
    REPLICA_MODEL_FRESHNESS,
    REPLICA_REQUESTS_TOTAL,
    REPLICA_RESPAWNS_TOTAL,
    REPLICA_UP,
    ROUTER_ADMISSION_TOTAL,
    TRACE_HEADER,
    FlightRecorder,
    fleet,
    get_registry,
    get_tracer,
    metrics_enabled,
    new_trace_id,
    scope,
    timeline,
)
from ..resilience.policy import CircuitBreaker
from .eventloop import EventLoopHTTPServer, callback_scope
from .http_base import (
    HTTPServerBase,
    PROMETHEUS_CTYPE,
    observability_response,
)
from .microbatch import EwmaEstimator

__all__ = [
    "Replica",
    "ReplicaSupervisor",
    "RouterConfig",
    "RouterServer",
    "spawn_replica",
    "wait_for_port_file",
]

logger = logging.getLogger(__name__)


class RouterConfig:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 health_interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 forward_timeout_s: float = 30.0,
                 breaker_failures: int = 3,
                 breaker_reset_s: float = 2.0,
                 max_connections: int = 1024,
                 workers: int = 16,
                 push_foldin_s: Optional[float] = None,
                 scrape_metrics: bool = True,
                 slo_ms: Optional[float] = None):
        self.host = host
        self.port = port
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.forward_timeout_s = forward_timeout_s
        self.breaker_failures = breaker_failures
        self.breaker_reset_s = breaker_reset_s
        self.max_connections = max_connections
        # blocking upstream forwards run on this many pool threads;
        # the loop thread itself never blocks on a replica
        self.workers = workers
        # optional timer driving the rolling fold-in push (the same
        # walk POST /admin/push-foldin triggers on demand)
        self.push_foldin_s = push_foldin_s
        # pio-lens: the health loop also pulls each replica's /metrics
        # and merges the parsed states into the router's own GET
        # /metrics (Prometheus-federation style — ONE scrape answers
        # for the fleet); slo_ms additionally arms the router-side
        # pio_slo_burn_rate{window} gauges on the forward round-trip
        # histogram
        self.scrape_metrics = scrape_metrics
        self.slo_ms = slo_ms


class Replica:
    """Router-side state for one replica: address, pooled keep-alive
    connections, breaker, health + last-seen status fields."""

    def __init__(self, name: str, host: str, port: int,
                 breaker_failures: int = 3, breaker_reset_s: float = 2.0,
                 timeout_s: float = 30.0):
        self.name = name
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failures,
            reset_timeout_s=breaker_reset_s,
        )
        self._lock = threading.Lock()
        self._pool: list[http.client.HTTPConnection] = []
        # healthy starts True: a fresh fleet serves immediately and the
        # first failed forward/health-check flips it (optimistic start
        # beats rejecting the first second of traffic)
        self.healthy = True
        self.last_status: dict = {}
        self.last_error: Optional[str] = None
        self.forwarded = 0
        self.errors = 0
        self.failovers = 0
        # pio-lens: the replica's last successfully scraped + parsed
        # /metrics state (a dump_state()-shaped dict).  Rebound whole
        # on every good scrape, never mutated — readers (the merged
        # exposition, the fleet tail table) see the old snapshot or
        # the new one, and a replica that dies mid-scrape keeps its
        # last good snapshot standing (cumulative values, so the
        # merged counters stay monotone).
        self.metrics_state: Optional[dict] = None
        self.scrape_errors = 0
        self.last_scrape_at: Optional[float] = None
        self.last_scrape_error: Optional[str] = None
        self._m_scrape_err = fleet.REPLICA_SCRAPE_ERRORS.labels(
            replica=name)
        self._m_up = REPLICA_UP.labels(replica=name)
        self._m_fresh = REPLICA_MODEL_FRESHNESS.labels(replica=name)
        self._m_ok = REPLICA_REQUESTS_TOTAL.labels(
            replica=name, outcome="ok")
        self._m_err = REPLICA_REQUESTS_TOTAL.labels(
            replica=name, outcome="error")
        self._m_fail = REPLICA_REQUESTS_TOTAL.labels(
            replica=name, outcome="failover")
        self._m_up.set(1.0)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _connect(self, timeout_s: Optional[float] = None
                 ) -> http.client.HTTPConnection:
        # fresh connections honor the CALLER's timeout (pio-lens fix):
        # a SIGSTOPped replica accepts the TCP handshake from its
        # kernel backlog and then never answers — with the default 30s
        # here, one stalled replica used to wedge every health sweep
        # (and the metrics scrape behind it) for 30s per tick
        c = http.client.HTTPConnection(
            self.host, self.port,
            timeout=timeout_s if timeout_s is not None
            else self.timeout_s,
        )
        c.connect()
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return c

    def request(self, method: str, path: str, body: Optional[bytes],
                headers: Optional[dict] = None,
                timeout_s: Optional[float] = None,
                tl=None) -> tuple[int, bytes, str]:
        """One upstream round trip on a pooled keep-alive connection.
        Transport trouble raises OSError/http.client exceptions — the
        router's failover signal; HTTP error statuses return normally
        (an application 4xx/5xx is the replica's answer, not a death).

        ``tl`` (a pulse Timeline, pio-lens) books the round trip's
        interior split: ``forward`` = pool/connect + request send,
        ``replica`` = waiting on the replica's response head (its
        serve time), ``read`` = draining the body."""
        with self._lock:
            conn = self._pool.pop() if self._pool else None
        if conn is None:
            conn = self._connect(timeout_s)
        elif timeout_s is not None and conn.sock is not None:
            conn.sock.settimeout(timeout_s)
        try:
            hdrs = {"Content-Type": "application/json"}
            if headers:
                hdrs.update(headers)
            conn.request(method, path, body, headers=hdrs)
            if tl is not None:
                tl.mark("forward")
            r = conn.getresponse()
            if tl is not None:
                tl.mark("replica")
            data = r.read()
            if tl is not None:
                tl.mark("read")
            ctype = r.getheader("Content-Type",
                                "application/json") or "application/json"
            status = r.status
        except BaseException:
            try:
                conn.close()
            except OSError:
                pass
            raise
        with self._lock:
            if len(self._pool) < 32:
                self._pool.append(conn)
            else:
                try:
                    conn.close()
                except OSError:
                    pass
        return status, data, ctype

    def mark_down(self, err: str) -> None:
        self.healthy = False
        self.last_error = err
        self.breaker.record_failure()
        self._m_up.set(0.0)
        # drop pooled connections: they point at a corpse
        with self._lock:
            pool, self._pool = self._pool, []
        for c in pool:
            try:
                c.close()
            except OSError:
                pass

    def mark_up(self, status: dict) -> None:
        self.healthy = True
        self.last_error = None
        self.last_status = status
        self.breaker.record_success()
        self._m_up.set(1.0)
        fresh = status.get("modelFreshnessSec")
        if fresh is not None:
            self._m_fresh.set(float(fresh))

    def scrape(self, timeout_s: float) -> bool:
        """Pull + parse this replica's ``/metrics`` into
        :attr:`metrics_state` (pio-lens).  Any failure — transport,
        HTTP status, exposition grammar — books a scrape error and
        leaves the previous snapshot standing; health marking is the
        health check's job, not the scrape's."""
        try:
            status, data, _ = self.request(
                "GET", "/metrics", None, timeout_s=timeout_s,
            )
            if status != 200:
                raise RuntimeError(f"/metrics answered {status}")
            state = fleet.parse_prometheus(data.decode())
        except Exception as e:
            self.scrape_errors += 1
            self.last_scrape_error = f"{type(e).__name__}: {e}"
            self._m_scrape_err.inc()
            return False
        self.metrics_state = state
        self.last_scrape_at = time.time()
        self.last_scrape_error = None
        return True

    def snapshot(self) -> dict:
        out = {
            "name": self.name,
            "url": self.url,
            "healthy": self.healthy,
            "breaker": self.breaker.state,
            "forwarded": self.forwarded,
            "errors": self.errors,
            "failovers": self.failovers,
        }
        if self.scrape_errors:
            out["scrapeErrors"] = self.scrape_errors
        if self.last_error:
            out["lastError"] = self.last_error
        st = self.last_status
        for src_key, dst_key in (
            ("engineInstanceId", "engineInstanceId"),
            ("requestCount", "requestCount"),
            ("modelFreshnessSec", "modelFreshnessSec"),
            ("foldinDeltasApplied", "foldinDeltasApplied"),
        ):
            if src_key in st:
                out[dst_key] = st[src_key]
        return out


class ReplicaSupervisor:
    """Respawn-on-death for the replica fleet (pio-scout satellite;
    ROADMAP item 1b): before this, a SIGKILLed replica stayed dead —
    masked by failover, but the fleet ran at N-1 until an operator
    acted.  The router's health loop ticks the supervisor every sweep;
    a replica whose *process* has exited is respawned through the same
    spawner ``deploy --replicas`` used, with capped exponential backoff
    between attempts so a crash-looping engine (bad model, OOM) cannot
    melt the box, and ``pio_replica_respawns_total{replica}`` books
    every successful respawn.

    The respawn itself (subprocess boot + port-file wait — seconds to
    minutes) runs on a per-replica background thread so one slow boot
    never stalls health sweeps for the rest of the fleet.
    """

    def __init__(self, spawner, waiter=None, backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0,
                 spawn_timeout_s: float = 180.0):
        # spawner(index) -> spawned dict (router.spawn_replica shape);
        # waiter(spawned) -> bound port (defaults to wait_for_port_file)
        self.spawner = spawner
        self.waiter = waiter or wait_for_port_file
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.spawn_timeout_s = spawn_timeout_s
        self._lock = threading.Lock()
        # replica name -> {"spawned", "index", "attempts", "next_try",
        #                  "busy"}
        self._procs: dict[str, dict] = {}
        self.respawns = 0

    def attach(self, replica: Replica, spawned: dict) -> None:
        with self._lock:
            self._procs[replica.name] = {
                "spawned": spawned,
                "index": spawned["index"],
                "attempts": 0,
                "next_try": 0.0,
                "busy": False,
            }

    def live_procs(self) -> list:
        """Every currently-tracked subprocess (fleet teardown reaps
        these, not the boot-time list — respawns replace entries)."""
        with self._lock:
            return [st["spawned"]["proc"] for st in self._procs.values()]

    def tick(self, replicas: list[Replica]) -> None:
        """One health-loop sweep: respawn any replica whose process
        has exited (past its backoff), reset backoff for replicas that
        are alive AND healthy again."""
        now = time.monotonic()
        for replica in replicas:
            with self._lock:
                st = self._procs.get(replica.name)
                if st is None or st["busy"]:
                    continue
                proc = st["spawned"]["proc"]
                if proc.poll() is None:
                    if replica.healthy:
                        st["attempts"] = 0
                    continue
                if now < st["next_try"]:
                    continue
                st["busy"] = True
            threading.Thread(
                target=self._respawn, args=(replica,),
                daemon=True, name=f"respawn-{replica.name}",
            ).start()

    def _respawn(self, replica: Replica) -> None:
        name = replica.name
        with self._lock:
            st = self._procs[name]
            index = st["index"]
            attempt = st["attempts"]
        try:
            spawned = self.spawner(index)
            port = self.waiter(spawned, timeout_s=self.spawn_timeout_s)
        except Exception as e:
            logger.warning("respawn of %s failed: %s", name, e)
            with self._lock:
                st["attempts"] += 1
                st["next_try"] = time.monotonic() + min(
                    self.backoff_cap_s,
                    self.backoff_base_s * (2.0 ** st["attempts"]),
                )
                st["busy"] = False
            return
        # point the router at the new process: update the port, drop
        # pooled connections to the corpse (mark_down does), and let
        # the next health tick flip it healthy
        replica.port = port
        replica.mark_down(f"respawned on port {port}; awaiting health")
        REPLICA_RESPAWNS_TOTAL.labels(replica=name).inc()
        with self._lock:
            st["spawned"] = spawned
            self.respawns += 1
            # successful respawns back off too: a crash-looping engine
            # respawns at the capped cadence, not as fast as it dies
            st["attempts"] += 1
            st["next_try"] = time.monotonic() + min(
                self.backoff_cap_s,
                self.backoff_base_s * (2.0 ** st["attempts"]),
            )
            st["busy"] = False
        logger.info("respawned %s on port %d", name, port)

    def summary(self) -> dict:
        with self._lock:
            return {
                "respawns": self.respawns,
                "tracked": len(self._procs),
                "backoffCapSec": self.backoff_cap_s,
            }


class RouterServer(HTTPServerBase):
    """The fleet front door; see module docstring."""

    server_name = "router"

    def __init__(self, replicas: list[Replica],
                 config: Optional[RouterConfig] = None,
                 supervisor: Optional[ReplicaSupervisor] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = replicas
        self.config = config or RouterConfig()
        self.supervisor = supervisor
        self._pool = None
        self._rr_lock = threading.Lock()
        self._rr = 0
        self._push_lock = threading.Lock()
        self._stop_event = threading.Event()
        self.start_time = time.time()  # wall clock: a TIMESTAMP
        self.request_count = 0
        self.unroutable = 0
        # router-level deadline admission (pio-scout satellite; ROADMAP
        # item 1b): the same EWMA estimator shape the micro-batcher
        # uses for device batches, fed with replica round-trip times —
        # a request whose ?timeout= budget the fleet demonstrably
        # cannot meet is answered a structured 503 HERE, without
        # burning a replica round trip on a doomed forward (today only
        # replicas shed).  Seeded 0: a cold router never sheds.
        self._ewma_forward = EwmaEstimator()
        self._ewma_lock = threading.Lock()
        self.admission_rejected = 0
        self._m_adm_ok = ROUTER_ADMISSION_TOTAL.labels(outcome="admitted")
        self._m_adm_rej = ROUTER_ADMISSION_TOTAL.labels(
            outcome="rejected")
        # pio-lens: the router's own flight recorder — worst-N proxied
        # requests with per-replica attribution (which replica served,
        # its round trip vs its self-reported segment split, the EWMA
        # estimate at admission time).  A separate instance from the
        # process-global recorder so an in-process replica's serve.query
        # offers never crowd out the fleet view.
        self.flight = FlightRecorder()
        self._m_forward = fleet.ROUTER_FORWARD_SECONDS.child()
        self._burn = None
        if self.config.slo_ms:
            self._burn = fleet.install_burn_rate(
                self._m_forward, self.config.slo_ms / 1e3
            )
        fleet.set_fleet_provider(self.fleet_payload)
        self._health_thread: Optional[threading.Thread] = None
        self._push_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def host(self) -> str:
        return self.config.host

    @property
    def port(self) -> int:
        return self.config.port

    @port.setter
    def port(self, v: int) -> None:
        self.config.port = v

    @property
    def max_connections(self) -> int:
        return self.config.max_connections

    def _build_httpd(self):
        import concurrent.futures

        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.config.workers,
                thread_name_prefix="router-fwd",
                initializer=scope.register_thread_role,
                initargs=("router_fwd",),
            )
        self._start_daemons()
        # pio-scope: the router is THE single-event-loop suspect at
        # fleet saturation (ROADMAP item 1) — always profile it
        scope.ensure_started()
        return EventLoopHTTPServer(
            (self.host, self.port), self._el_handle,
            max_connections=self.config.max_connections,
            name="router",
        )

    def _start_daemons(self) -> None:
        if self._health_thread is None:
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True, name="router-health"
            )
            self._health_thread.start()
        if self.config.push_foldin_s and self._push_thread is None:
            self._push_thread = threading.Thread(
                target=self._push_loop, daemon=True, name="router-push"
            )
            self._push_thread.start()

    def stop(self) -> None:
        super().stop()
        self._stop_event.set()
        # clear the provider only if WE are still the installed one (a
        # second router in the same process may have replaced it)
        if getattr(fleet, "_fleet_provider", None) == self.fleet_payload:
            fleet.set_fleet_provider(None)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    # -- health ------------------------------------------------------------
    def check_replica(self, replica: Replica) -> bool:
        try:
            status, data, _ = replica.request(
                "GET", "/", None,
                timeout_s=self.config.health_timeout_s,
            )
            if status != 200:
                replica.mark_down(f"status {status}")
                return False
            replica.mark_up(json.loads(data.decode()))
            return True
        except Exception as e:
            replica.mark_down(f"{type(e).__name__}: {e}")
            return False

    def check_all(self) -> None:
        for r in self.replicas:
            self.check_replica(r)

    def scrape_all(self) -> None:
        """pio-lens: pull every replica's /metrics on the pooled
        keep-alive connections.  A dead replica's scrape fails fast
        (connection refused — one attempt per sweep, same cost as its
        health probe), books ``pio_replica_scrape_errors_total`` and
        leaves its last good snapshot standing in the merged
        exposition — cumulative values, so the fleet counters stay
        monotone through the death."""
        for r in self.replicas:
            r.scrape(self.config.health_timeout_s)

    def _health_loop(self) -> None:
        scope.register_thread_role("health_loop")
        while not self._stop_event.wait(self.config.health_interval_s):
            try:
                self.check_all()
            except Exception:
                logger.exception("router health sweep failed")
            if self.config.scrape_metrics:
                try:
                    self.scrape_all()
                except Exception:
                    logger.exception("router metrics scrape failed")
            if self.supervisor is not None:
                try:
                    self.supervisor.tick(self.replicas)
                except Exception:
                    logger.exception("replica supervisor tick failed")

    # -- rolling fold-in push ---------------------------------------------
    def push_foldin(self) -> dict:
        """Walk the fleet ONE replica at a time, telling each to apply
        any pending fold-in delta links now (``POST /foldin/apply``).
        Sequential by construction — mid-push, at most the one replica
        currently applying is busy (and the apply is in-place anyway),
        so availability never drops below N-1."""
        results = []
        with self._push_lock:  # one rolling push at a time
            for r in self.replicas:
                if not r.healthy:
                    results.append({
                        "replica": r.name, "skipped": "unhealthy",
                    })
                    continue
                try:
                    status, data, _ = r.request(
                        "POST", "/foldin/apply", b"{}",
                        timeout_s=self.config.forward_timeout_s,
                    )
                    body = json.loads(data.decode())
                    entry = {"replica": r.name, "status": status}
                    entry.update({
                        k: body[k] for k in
                        ("applied", "modelFreshnessSec",
                         "foldinDeltasApplied")
                        if k in body
                    })
                    results.append(entry)
                    fresh = body.get("modelFreshnessSec")
                    if fresh is not None:
                        r._m_fresh.set(float(fresh))
                except Exception as e:
                    r.mark_down(f"{type(e).__name__}: {e}")
                    results.append({
                        "replica": r.name,
                        "error": f"{type(e).__name__}: {e}",
                    })
        return {"pushed": results}

    def _push_loop(self) -> None:
        scope.register_thread_role("push_loop")
        while not self._stop_event.wait(self.config.push_foldin_s):
            try:
                self.push_foldin()
            except Exception:
                logger.exception("rolling fold-in push failed")

    # -- forwarding --------------------------------------------------------
    def _candidates(self) -> list[Replica]:
        with self._rr_lock:
            self._rr += 1
            start = self._rr
        n = len(self.replicas)
        order = [self.replicas[(start + i) % n] for i in range(n)]
        healthy = [r for r in order if r.healthy]
        # last resort: unhealthy replicas whose breaker grants a probe
        # (a recovered replica starts taking traffic before the next
        # health tick)
        probes = [r for r in order
                  if not r.healthy and r.breaker.allow()]
        return healthy + probes

    def _broadcast_post(self, target: str, body: bytes, respond) -> None:
        """POST ``body`` to ``target`` on every healthy replica from
        the forward pool and answer the merged per-replica results —
        the admin fan-out shared by the weights and tenant-lifecycle
        routes."""
        pool = self._pool
        if pool is None:
            respond(503, {"message": "router is stopping"})
            return

        def broadcast():
            results = []
            for r in self.replicas:
                if not r.healthy:
                    results.append({
                        "replica": r.name, "skipped": "unhealthy",
                    })
                    continue
                try:
                    status, data, _ = r.request(
                        "POST", target, body,
                        timeout_s=self.config.forward_timeout_s,
                    )
                    entry = {"replica": r.name, "status": status}
                    try:
                        entry.update(json.loads(data.decode()))
                    except (json.JSONDecodeError, UnicodeDecodeError):
                        pass
                    results.append(entry)
                except Exception as e:
                    r.mark_down(f"{type(e).__name__}: {e}")
                    results.append({
                        "replica": r.name,
                        "error": f"{type(e).__name__}: {e}",
                    })
            try:
                respond(200, {"pushed": results})
            except RuntimeError:
                pass

        try:
            pool.submit(broadcast)
        except RuntimeError:
            respond(503, {"message": "router is stopping"})

    def _forward_query(self, path_qs: str, body: bytes,
                       trace_id: Optional[str], respond,
                       tl=None, est_at_admission: float = 0.0) -> None:
        """Worker-pool half of the hot path: try candidates in order
        until one answers; transport failures fail over with the
        replica marked down.

        pio-lens: the request's Timeline accumulates the
        ``forward/replica/read`` split (inside ``Replica.request``),
        the successful round trip feeds the forward histogram (with
        the trace id as its bucket exemplar) and a ``router.forward``
        span, and the finished request is offered to the router's
        flight recorder with the serving replica's name + the EWMA
        estimate admission saw — the per-replica tail attribution
        ROADMAP 1(c) asks for."""
        headers = {TRACE_HEADER: trace_id} if trace_id else None
        hdrs_out = [(TRACE_HEADER, trace_id)] if trace_id else []
        candidates = self._candidates()
        last_err = "no replicas configured"
        failed: list[str] = []
        for i, replica in enumerate(candidates):
            t0 = time.perf_counter()
            wall0 = time.time()
            try:
                status, data, ctype = replica.request(
                    "POST", path_qs, body, headers=headers,
                    timeout_s=self.config.forward_timeout_s, tl=tl,
                )
            except Exception as e:
                last_err = f"{replica.name}: {type(e).__name__}: {e}"
                replica.errors += 1
                replica._m_fail.inc()
                replica.failovers += 1
                replica.mark_down(last_err)
                failed.append(replica.name)
                continue
            if not replica.healthy:
                replica.mark_up(replica.last_status)
            replica.forwarded += 1
            rt = time.perf_counter() - t0
            # feed the admission estimator with the fleet's actual
            # round-trip time (success paths only: a failover's
            # timeout would teach the estimator to shed everything)
            with self._ewma_lock:
                self._ewma_forward.observe(rt)
            self._m_forward.observe(rt, exemplar=trace_id)
            (replica._m_ok if status < 500 else replica._m_err).inc()
            tracer = get_tracer()
            tracer.record(
                "router.forward", rt, trace_id=trace_id,
                attrs={"replica": replica.name, "status": status},
                start=wall0,
            )
            if tl is not None:
                total = tl.elapsed()
                attrs = {
                    "replica": replica.name,
                    "status": status,
                    "ewmaAtAdmissionSec": round(est_at_admission, 6),
                    "roundTripSec": round(rt, 6),
                    "segmentsMs": tl.snapshot_ms(),
                }
                if failed:
                    # the tail-attribution fix for failover: a request
                    # that waited out a stalled replica's timeout and
                    # then succeeded elsewhere names the replica that
                    # ATE the time, not just the one that answered
                    attrs["failedReplicas"] = failed
                if i:
                    attrs["failovers"] = i
                tracer.record(
                    "router.request", total, trace_id=trace_id,
                    attrs=attrs, start=time.time() - total,
                )
                # offer AFTER the spans land so an admitted record's
                # captured tree holds them
                self.flight.offer(
                    trace_id, total, name="router.request", attrs=attrs,
                )
            try:
                respond(status, data, ctype=ctype,
                        extra_headers=hdrs_out, tl=tl)
            except RuntimeError:
                pass
            return
        self.unroutable += 1
        try:
            respond(503, {
                "message": f"no replica available ({last_err})",
                "error": "NoReplicaAvailable",
            }, extra_headers=hdrs_out + [("Retry-After", "1")])
        except RuntimeError:
            pass

    # -- pio-lens: merged exposition + fleet tail view ---------------------
    def render_fleet_metrics(self) -> bytes:
        """The router's ``GET /metrics`` body: local registry state
        merged with every replica's last scraped snapshot via
        ``registry.merge_states`` (counters/histograms sum exactly,
        gauges gain ``{replica}`` labels) and rendered through the ONE
        shared renderer — so ``pio_queries_total`` on the router equals
        the sum of the replicas' and percentile re-derivation over the
        merged buckets is exact.  A schema drift between replicas
        degrades to the local exposition LOUDLY rather than 500ing the
        scrape."""
        tagged = [("router", get_registry().dump_state())]
        for r in self.replicas:
            state = r.metrics_state
            if state is not None:
                tagged.append((r.name, state))
        try:
            return fleet.render_fleet(tagged).encode()
        except ValueError as e:
            logger.warning(
                "fleet metrics merge failed (%s); serving the "
                "router-local exposition", e,
            )
            return get_registry().render_prometheus().encode()

    def _replica_tail_entry(self, r: Replica) -> dict:
        entry = r.snapshot()
        entry["respawns"] = REPLICA_RESPAWNS_TOTAL.labels(
            replica=r.name).value()
        state = r.metrics_state
        if state is not None:
            hist = fleet.state_histogram(
                state, "pio_query_latency_seconds")
            if hist and hist["count"]:
                entry["p50Ms"] = round(
                    fleet.hist_quantile(hist, 50) * 1e3, 3)
                entry["p99Ms"] = round(
                    fleet.hist_quantile(hist, 99) * 1e3, 3)
                entry["latencyCount"] = hist["count"]
            entry["queriesTotal"] = fleet.state_counter_total(
                state, "pio_queries_total")
            if r.last_scrape_at is not None:
                entry["scrapeAgeSec"] = round(
                    max(time.time() - r.last_scrape_at, 0.0), 3)
        if r.last_scrape_error:
            entry["lastScrapeError"] = r.last_scrape_error
        return entry

    def _enrich_worst(self, worst: list) -> list:
        """Lazily join each worst-N record with the serving replica's
        OWN view of that trace: ``GET /debug/flight?trace=<id>`` on
        the replica answers its flight record, whose ``segmentsMs``
        decomposition sits next to the router's round trip — the
        queue-vs-device split of a fleet tail entry without shipping
        every span through the router.  Fetched once per record and
        cached back into the router's flight attrs."""
        by_name = {r.name: r for r in self.replicas}
        for w in worst[:8]:
            attrs = w.get("attrs") or {}
            if "replicaSegmentsMs" in attrs or "replica" not in attrs:
                continue
            replica = by_name.get(attrs["replica"])
            if replica is None or not replica.healthy:
                continue
            try:
                status, data, _ = replica.request(
                    "GET",
                    f"/debug/flight?trace="
                    f"{urllib.parse.quote(w['traceId'])}",
                    None, timeout_s=self.config.health_timeout_s,
                )
                if status != 200:
                    continue
                rec = json.loads(data.decode()).get("record")
            except Exception:
                continue
            if not rec:
                continue
            extra = {
                "replicaDurationSec": rec.get("durationSec"),
                "replicaSegmentsMs": (rec.get("attrs") or {}).get(
                    "segmentsMs"),
            }
            self.flight.annotate(w["traceId"], extra)
            attrs.update(extra)
            w["attrs"] = attrs
        return worst

    def fleet_payload(self) -> dict:
        """``GET /debug/fleet``: how is the fleet doing and who is
        slow — per-replica tail table (scrape-derived p50/p99, breaker
        + respawn state) plus the router flight recorder's worst-N
        with per-replica attribution and lazily fetched replica
        segment splits."""
        summary = self.flight.summary()
        out = {
            "role": "router",
            "replicas": [
                self._replica_tail_entry(r) for r in self.replicas
            ],
            "healthyReplicas": sum(r.healthy for r in self.replicas),
            "requestCount": self.request_count,
            "unroutable": self.unroutable,
            "admissionRejected": self.admission_rejected,
            "ewmaForwardSec": self._ewma_forward.value,
            "scrapeErrors": sum(r.scrape_errors for r in self.replicas),
            "flight": {
                "capacity": summary["capacity"],
                "offers": summary["offers"],
                "admissions": summary["admissions"],
            },
            "worst": self._enrich_worst(summary["worst"]),
        }
        if self.config.slo_ms:
            out["sloMs"] = self.config.slo_ms
            if self._burn is not None:
                out["burnRate"] = {
                    name: round(self._burn.rate(secs), 4)
                    for name, secs in fleet.BURN_WINDOWS
                }
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.summary()
        return out

    # -- http --------------------------------------------------------------
    def status_json(self) -> dict:
        out = {
            "status": "alive",
            "role": "router",
            "replicas": [r.snapshot() for r in self.replicas],
            "healthyReplicas": sum(r.healthy for r in self.replicas),
            "requestCount": self.request_count,
            "unroutable": self.unroutable,
            "admissionRejected": self.admission_rejected,
            "ewmaForwardSec": self._ewma_forward.value,
            "startTime": self.start_time,
            "maxConnections": self.config.max_connections,
        }
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.summary()
        return out

    @callback_scope
    def _el_handle(self, req, respond) -> None:
        u = urllib.parse.urlparse(req.path)
        path = u.path
        if req.method == "POST" and path == "/queries.json":
            self.request_count += 1  # loop-thread only: no lock needed
            # pio-lens: the router MINTS a trace id when the client
            # didn't bring one — every proxied request is stitchable
            # across router + replica journals (tools/tracecat.py)
            tid = (req.header(TRACE_HEADER) or "").strip() \
                or new_trace_id()
            body = req.body
            tl = timeline.Timeline("router")
            # router-level deadline admission: a ?timeout= request the
            # EWMA forward estimate already exceeds is a doomed
            # round-trip — answer the structured 503 the replica edge
            # would have, one hop earlier and without spending a
            # replica on it.  No timeout (or a cold estimator) admits.
            est = self._ewma_forward.value
            tv = urllib.parse.parse_qs(u.query).get("timeout")
            if tv:
                try:
                    budget = float(tv[0])
                except ValueError:
                    budget = None
                if budget is not None and est > 0.0 and (
                    budget <= 0.0 or est > budget
                ):
                    self.admission_rejected += 1  # loop-thread only
                    self._m_adm_rej.inc()
                    respond(503, {
                        "message": (
                            f"estimated fleet round-trip "
                            f"{est * 1e3:.1f}ms exceeds the "
                            f"{budget * 1e3:.1f}ms request budget"
                        ),
                        "error": "AdmissionRejected",
                    }, extra_headers=[("Retry-After", "1"),
                                      (TRACE_HEADER, tid)])
                    return
                self._m_adm_ok.inc()
            tl.mark("admission")
            pool = self._pool
            if pool is None:
                respond(503, {"message": "router is stopping"})
                return
            try:
                pool.submit(
                    self._forward_query, req.path, body, tid, respond,
                    tl, est,
                )
            except RuntimeError:
                respond(503, {"message": "router is stopping"})
            return
        if req.method == "POST" and path == "/admin/push-foldin":
            pool = self._pool
            if pool is None:
                respond(503, {"message": "router is stopping"})
                return

            def push():
                try:
                    respond(200, self.push_foldin())
                except RuntimeError:
                    pass
                except Exception as e:
                    logger.exception("push-foldin failed")
                    try:
                        respond(500, {"message": str(e)})
                    except RuntimeError:
                        pass

            try:
                pool.submit(push)
            except RuntimeError:
                respond(503, {"message": "router is stopping"})
            return
        if req.method == "POST" and path in ("/admin/tenants/weights",
                                             "/admin/tenants"):
            # pio-hive admin broadcast: a variant-weight update or a
            # tenant add/remove fans out to EVERY replica so the whole
            # fleet stays identical (sticky assignment is pure hash +
            # weights — same registry state everywhere == same variant
            # for every user everywhere)
            target = ("/tenants/weights"
                      if path == "/admin/tenants/weights"
                      else "/admin/tenants")
            self._broadcast_post(target, req.body, respond)
            return
        if req.method == "GET" and path == "/debug/tenants":
            # fleet view: each replica's registry document keyed by
            # replica name (one curl answers "which replica holds which
            # tenants resident, and what are the A/B rates")
            pool = self._pool
            if pool is None:
                respond(503, {"message": "router is stopping"})
                return

            def gather():
                out = {}
                for r in self.replicas:
                    try:
                        status, data, _ = r.request(
                            "GET", "/debug/tenants", None,
                            timeout_s=self.config.health_timeout_s,
                        )
                        out[r.name] = (
                            json.loads(data.decode()) if status == 200
                            else {"status": status}
                        )
                    except Exception as e:
                        out[r.name] = {
                            "error": f"{type(e).__name__}: {e}",
                        }
                try:
                    respond(200, {"replicas": out})
                except RuntimeError:
                    pass

            try:
                pool.submit(gather)
            except RuntimeError:
                respond(503, {"message": "router is stopping"})
            return
        if req.method == "POST" and path == "/stop":
            respond(200, {"message": "stopping"})
            threading.Thread(target=self.stop, daemon=True).start()
            return
        if req.method == "GET" and path == "/metrics":
            # pio-lens: the router's exposition is the FLEET's — local
            # registry state merged with every replica's last scraped
            # snapshot (counters/histograms sum, gauges labeled
            # {replica}); render on the pool, not the loop
            if not metrics_enabled():
                respond(404, {"message":
                              "metrics disabled (--no-metrics)"})
                return
            pool = self._pool
            if pool is None:
                respond(503, {"message": "router is stopping"})
                return

            def metrics():
                try:
                    respond(200, self.render_fleet_metrics(),
                            ctype=PROMETHEUS_CTYPE)
                except RuntimeError:
                    pass

            try:
                pool.submit(metrics)
            except RuntimeError:
                respond(503, {"message": "router is stopping"})
            return
        if req.method == "GET" and path == "/debug/fleet":
            # the fleet tail view: per-replica p50/p99 + worst-N with
            # replica attribution; lazy replica /debug/flight fetches
            # block, so pool it
            pool = self._pool
            if pool is None:
                respond(503, {"message": "router is stopping"})
                return

            def dbg():
                try:
                    respond(200, self.fleet_payload())
                except RuntimeError:
                    pass
                except Exception as e:
                    logger.exception("/debug/fleet failed")
                    try:
                        respond(500, {"message": str(e)})
                    except RuntimeError:
                        pass

            try:
                pool.submit(dbg)
            except RuntimeError:
                respond(503, {"message": "router is stopping"})
            return
        if req.method == "GET":
            ans = observability_response(path, u.query)
            if ans is not None:
                # /debug/profile can block for seconds — pool, not loop
                pool = self._pool

                def obs():
                    code, payload, ctype = observability_response(
                        path, u.query
                    )
                    try:
                        respond(code, payload,
                                ctype=ctype or "application/json")
                    except RuntimeError:
                        pass

                if path == "/debug/profile" and pool is not None:
                    pool.submit(obs)
                else:
                    code, payload, ctype = ans
                    respond(code, payload,
                            ctype=ctype or "application/json")
                return
            if path == "/":
                respond(200, self.status_json())
                return
        respond(404, {"message": "not found"})


# -- replica process spawning ----------------------------------------------


def spawn_replica(engine_json, index: int, coord_dir,
                  extra_args=(), env=None,
                  python: str = sys.executable,
                  engine_name=None) -> dict:
    """Launch one replica as a real subprocess (`pio-tpu deploy` on an
    ephemeral port, announcing it through a port file in
    ``coord_dir``).  ``engine_name`` dispatches a pio-forge registry
    engine (``deploy --engine NAME``) instead of an engine.json path.
    Returns ``{"proc", "port_file", "log_path", "index"}``; pair with
    :func:`wait_for_port_file`."""
    coord_dir = Path(coord_dir)
    coord_dir.mkdir(parents=True, exist_ok=True)
    port_file = coord_dir / f"replica-{index}.port"
    log_path = coord_dir / f"replica-{index}.log"
    # the child must resolve predictionio_tpu regardless of caller cwd
    import os as _os

    pkg_root = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(env if env is not None else _os.environ)
    pp = env.get("PYTHONPATH", "")
    if pkg_root not in pp.split(_os.pathsep):
        env["PYTHONPATH"] = (
            pkg_root + (_os.pathsep + pp if pp else "")
        )
    engine_arg = (
        ["--engine", str(engine_name)] if engine_name
        else ["--engine-json", str(engine_json)]
    )
    cmd = [
        python, "-m", "predictionio_tpu.cli.main", "deploy",
        *engine_arg,
        "--ip", "127.0.0.1", "--port", "0",
        "--port-file", str(port_file),
        *extra_args,
    ]
    log_f = open(log_path, "w")
    proc = subprocess.Popen(
        cmd, stdout=log_f, stderr=subprocess.STDOUT, env=env,
    )
    log_f.close()
    return {"proc": proc, "port_file": port_file,
            "log_path": log_path, "index": index}


def wait_for_port_file(spawned: dict, timeout_s: float = 180.0) -> int:
    """Block until the replica announces its bound port (or dies)."""
    port_file = spawned["port_file"]
    proc = spawned["proc"]
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if port_file.exists():
            text = port_file.read_text().strip()
            if text:
                return int(text)
        if proc.poll() is not None:
            tail = ""
            try:
                tail = Path(spawned["log_path"]).read_text()[-2000:]
            except OSError:
                pass
            raise RuntimeError(
                f"replica {spawned['index']} exited rc={proc.returncode} "
                f"before announcing a port; log tail:\n{tail}"
            )
        time.sleep(0.05)
    raise TimeoutError(
        f"replica {spawned['index']} did not announce a port within "
        f"{timeout_s}s"
    )
