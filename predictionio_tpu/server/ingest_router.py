"""pio-levee: fault-isolated multi-process ingest edge.

``pio-tpu eventserver --workers N`` boots N shard-owner WORKER
processes (each a full `EventServer` with its own interpreter, its own
ingest WAL, and a fixed subset of the sharded store's entity-hash
shards) and ONE router in front.  The serving side got this shape in
pio-surge (`server/router.py`); this is the write-path analogue with
one decisive difference: **writes cannot fail over**.  A query can be
retried on any replica; an event write belongs to exactly one shard
owner (that process holds the shard's sqlite writer lock and WAL), so
when the owner is down the honest answer is a structured
``503 {"error": "ShardUnavailable", "shard": I}`` + ``Retry-After`` on
that shard's entities — and 2xx everywhere else.  One dead worker is a
partial outage of 1/N of the keyspace, never a fleet outage and never
silent loss (acknowledged events live in the dead owner's WAL and
replay when its replacement boots).

* **Routing**: the entity-hash routing table is the STORE's own
  ``crc32(entity_type ++ entity_id) % n_shards`` (one definition,
  `sharded_events._shard_ix`), striped over workers
  (``shard % n_workers``).  Single-event POSTs route whole; batch
  POSTs split per owner, forward concurrently-ordered subsets, and
  re-merge per-event statuses positionally.  Entity-scoped reads go to
  the owner (whose WAL barrier gives read-your-writes); keyspace-wide
  reads round-robin healthy workers (sqlite files take cross-process
  readers freely — ownership gates writers).
* **Health + respawn**: the router's health loop probes each worker,
  scrapes its ``/metrics``, maintains ``pio_ingest_worker_up{worker}``
  and feeds the shared `router.ReplicaSupervisor` so a SIGKILLed
  worker respawns (same wal_dir → boot replay folds its acknowledged
  backlog into sqlite before the port announce).
* **Federation**: ``GET /metrics`` merges worker snapshots via
  ``merge_states(gauge_label="worker")`` (counters/histograms sum
  exactly, gauges gain ``{worker}``); ``GET /stats.json`` merges the
  workers' payloads via `stats.merge_stats_payloads`.  Both keep a
  dead worker's last-good snapshot standing, so fleet counters are
  monotone through a death (the pio-lens discipline).

The router rides the event-loop edge: the loop thread parses and
routes; every blocking upstream hop runs on a bounded pool.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
from pathlib import Path
from typing import Optional

from ..obs import (
    INGEST_FORWARD_SECONDS,
    INGEST_SHARD_UNAVAILABLE_TOTAL,
    INGEST_WORKER_UP,
    TRACE_HEADER,
    get_flight_recorder,
    get_registry,
    metrics_enabled,
    new_trace_id,
    scope,
)
from ..obs.registry import merge_states, render_state
from ..storage.sharded_events import _shard_ix
from .eventloop import EventLoopHTTPServer, callback_scope
from .http_base import (
    HTTPServerBase,
    PROMETHEUS_CTYPE,
    observability_response,
)
from .router import Replica, ReplicaSupervisor, wait_for_port_file
from .stats import merge_stats_payloads
from .webhooks import (
    FORM_CONNECTORS,
    JSON_CONNECTORS,
    ConnectorError,
    to_event,
)

__all__ = [
    "IngestRouterConfig",
    "IngestRouterServer",
    "IngestWorker",
    "shards_for_worker",
    "spawn_ingest_worker",
]

logger = logging.getLogger(__name__)


def shards_for_worker(index: int, n_workers: int,
                      n_shards: int) -> list[int]:
    """Striped ownership: worker i owns every shard ≡ i (mod N).  With
    the crc32 entity hash distributing entities uniformly, striping
    keeps per-worker load within noise of even for any N ≤ shards."""
    return [s for s in range(n_shards) if s % n_workers == index]


class IngestWorker(Replica):
    """One shard-owner worker, as the router sees it: the pooled-
    connection `Replica` surface plus its owned-shard set and the
    last-good ``/stats.json`` payload (per access key) that keeps the
    federated stats monotone through its death."""

    def __init__(self, name: str, host: str, port: int,
                 shards: list[int], index: int, **kw):
        super().__init__(name, host, port, **kw)
        self.shards = list(shards)
        self.index = index
        # accessKey-scoped query string -> last good /stats.json body;
        # rebound whole per fetch, never mutated (readers see old or
        # new — the metrics_state discipline)
        self.last_stats: dict[str, dict] = {}
        self._m_worker_up = INGEST_WORKER_UP.labels(worker=name)
        self._m_worker_up.set(1.0)

    def mark_down(self, err: str) -> None:
        super().mark_down(err)
        self._m_worker_up.set(0.0)

    def mark_up(self, status: dict) -> None:
        super().mark_up(status)
        self._m_worker_up.set(1.0)


class IngestRouterConfig:
    def __init__(self, host: str = "127.0.0.1", port: int = 7070,
                 n_shards: int = 4,
                 health_interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 forward_timeout_s: float = 30.0,
                 max_connections: int = 1024,
                 workers: int = 16,
                 scrape_metrics: bool = True,
                 retry_after_s: int = 2):
        self.host = host
        self.port = port
        self.n_shards = n_shards
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.forward_timeout_s = forward_timeout_s
        self.max_connections = max_connections
        # pool threads for blocking upstream forwards
        self.workers = workers
        self.scrape_metrics = scrape_metrics
        # the Retry-After a dead shard answers with — sized for a
        # supervisor respawn (sub-second spawn + WAL replay), not a
        # lock blip
        self.retry_after_s = retry_after_s


class IngestRouterServer(HTTPServerBase):
    """The ingest fleet's front door; see module docstring."""

    server_name = "ingest-router"

    def __init__(self, workers: list[IngestWorker],
                 config: Optional[IngestRouterConfig] = None,
                 supervisor: Optional[ReplicaSupervisor] = None):
        if not workers:
            raise ValueError("ingest router needs at least one worker")
        self.workers = workers
        self.config = config or IngestRouterConfig()
        self.supervisor = supervisor
        # shard -> owning worker, built once: ownership is fixed for
        # the fleet's lifetime (respawns keep their index)
        self.shard_owner: dict[int, IngestWorker] = {}
        for w in workers:
            for s in w.shards:
                if s in self.shard_owner:
                    raise ValueError(
                        f"shard {s} claimed by both "
                        f"{self.shard_owner[s].name} and {w.name}"
                    )
                self.shard_owner[s] = w
        missing = [s for s in range(self.config.n_shards)
                   if s not in self.shard_owner]
        if missing:
            raise ValueError(f"shards {missing} have no owner")
        self._pool = None
        self._rr_lock = threading.Lock()
        self._rr = 0
        self._stop_event = threading.Event()
        self.start_time = time.time()
        self.request_count = 0
        self.shard_unavailable = 0
        self._m_forward = INGEST_FORWARD_SECONDS.child()
        # pio-scope: the ingest router is its own process with no
        # serve.query traffic, so the process-global recorder IS the
        # ingest worst-N view — and the shared /debug/flight mount
        # serves it with no extra routing code.  Offers carry the
        # owning worker + shard, so a slow ingest tail line names its
        # shard owner outright.
        self.flight = get_flight_recorder()
        self._health_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def host(self) -> str:
        return self.config.host

    @property
    def port(self) -> int:
        return self.config.port

    @port.setter
    def port(self, v: int) -> None:
        self.config.port = v

    @property
    def max_connections(self) -> int:
        return self.config.max_connections

    def _build_httpd(self):
        import concurrent.futures

        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.config.workers,
                thread_name_prefix="ingest-fwd",
                initializer=scope.register_thread_role,
                initargs=("ingest_worker",),
            )
        scope.ensure_started()
        if self._health_thread is None:
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True,
                name="ingest-router-health",
            )
            self._health_thread.start()
        return EventLoopHTTPServer(
            (self.host, self.port), self._el_handle,
            max_connections=self.config.max_connections,
            name="ingest-router",
        )

    def stop(self) -> None:
        super().stop()
        self._stop_event.set()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    # -- health ------------------------------------------------------------
    def check_worker(self, w: IngestWorker) -> bool:
        try:
            status, data, _ = w.request(
                "GET", "/", None,
                timeout_s=self.config.health_timeout_s,
            )
            if status != 200:
                w.mark_down(f"status {status}")
                return False
            w.mark_up(json.loads(data.decode()))
            return True
        except Exception as e:
            w.mark_down(f"{type(e).__name__}: {e}")
            return False

    def _health_loop(self) -> None:
        scope.register_thread_role("health_loop")
        while not self._stop_event.wait(self.config.health_interval_s):
            for w in self.workers:
                try:
                    self.check_worker(w)
                except Exception:
                    logger.exception("worker health check failed")
            if self.config.scrape_metrics:
                for w in self.workers:
                    try:
                        w.scrape(self.config.health_timeout_s)
                    except Exception:
                        logger.exception("worker metrics scrape failed")
            if self.supervisor is not None:
                try:
                    self.supervisor.tick(self.workers)
                except Exception:
                    logger.exception("worker supervisor tick failed")

    # -- routing -----------------------------------------------------------
    def owner_of(self, entity_type: str, entity_id: str) -> IngestWorker:
        six = _shard_ix(entity_type, entity_id, self.config.n_shards)
        return self.shard_owner[six]

    def _any_healthy(self) -> Optional[IngestWorker]:
        with self._rr_lock:
            self._rr += 1
            start = self._rr
        n = len(self.workers)
        for i in range(n):
            w = self.workers[(start + i) % n]
            if w.healthy:
                return w
        return None

    def _unavailable_payload(self, w: IngestWorker, six: int) -> dict:
        return {
            "message": (
                f"shard {six} unavailable: owner {w.name} is down "
                f"({w.last_error or 'no heartbeat'})"
            ),
            "error": "ShardUnavailable",
            "shard": six,
        }

    def _retry_hdr(self) -> list[tuple[str, str]]:
        return [("Retry-After", str(self.config.retry_after_s))]

    def _book_unavailable(self, six: int, n: int = 1) -> None:
        self.shard_unavailable += n
        INGEST_SHARD_UNAVAILABLE_TOTAL.labels(shard=str(six)).inc(n)

    def _forward(self, w: IngestWorker, method: str, path_qs: str,
                 body: Optional[bytes],
                 trace_id: Optional[str] = None) -> tuple[int, bytes, str]:
        """One worker round trip; transport failure marks the worker
        down and re-raises (the caller answers ShardUnavailable — a
        write's owner is the ONLY process holding its shards, so there
        is no second candidate to try)."""
        t0 = time.perf_counter()
        try:
            out = w.request(
                method, path_qs, body,
                headers={TRACE_HEADER: trace_id} if trace_id else None,
                timeout_s=self.config.forward_timeout_s,
            )
        except Exception as e:
            w.errors += 1
            w.mark_down(f"{type(e).__name__}: {e}")
            raise
        if not w.healthy:
            w.mark_up(w.last_status)
        w.forwarded += 1
        self._m_forward.observe(time.perf_counter() - t0)
        return out

    # -- write path (pool side) -------------------------------------------
    def _offer_flight(self, trace_id: Optional[str], t0: float,
                      **attrs) -> None:
        """pio-scope: offer one finished ingest request to the worst-N
        recorder, attributed to its shard owner.  The common (fast)
        case is one lock + one float compare inside the recorder; an
        admitted slow request gets its wall window joined against the
        profiler ring (``dominantStacks``) so the flight record says
        what the router was doing while the request crawled."""
        try:
            self.flight.offer(
                trace_id, time.perf_counter() - t0,
                name="ingest.request",
                attrs={k: v for k, v in attrs.items() if v is not None},
            )
        except Exception:
            logger.exception("ingest flight offer failed")

    def _post_event(self, path_qs: str, body: bytes, respond,
                    trace_id: Optional[str] = None) -> None:
        t0 = time.perf_counter()
        try:
            payload = json.loads(body.decode())
            et = str(payload["entityType"])
            ei = str(payload["entityId"])
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            self._respond_quiet(
                respond, 400, {"message": f"invalid event body: {e}"}
            )
            return
        six = _shard_ix(et, ei, self.config.n_shards)
        w = self.shard_owner[six]
        if not w.healthy:
            self._book_unavailable(six)
            self._respond_quiet(
                respond, 503, self._unavailable_payload(w, six),
                extra_headers=self._retry_hdr(),
            )
            self._offer_flight(trace_id, t0, worker=w.name, shard=six,
                               status=503, outcome="shard_unavailable")
            return
        try:
            status, data, ctype = self._forward(
                w, "POST", path_qs, body, trace_id=trace_id
            )
        except Exception:
            self._book_unavailable(six)
            self._respond_quiet(
                respond, 503, self._unavailable_payload(w, six),
                extra_headers=self._retry_hdr(),
            )
            self._offer_flight(trace_id, t0, worker=w.name, shard=six,
                               status=503, outcome="forward_error")
            return
        self._respond_quiet(
            respond, status, data, ctype=ctype,
            extra_headers=[(TRACE_HEADER, trace_id)] if trace_id else (),
        )
        self._offer_flight(trace_id, t0, worker=w.name, shard=six,
                           status=status, events=1)

    def _post_batch(self, path_qs: str, body: bytes, respond,
                    trace_id: Optional[str] = None) -> None:
        t0 = time.perf_counter()
        try:
            items = json.loads(body.decode())
            if not isinstance(items, list):
                raise ValueError("batch body must be a JSON array")
        except (ValueError, UnicodeDecodeError) as e:
            self._respond_quiet(respond, 400, {"message": str(e)})
            return
        if len(items) > 50:
            self._respond_quiet(respond, 400, {
                "message": "batch limited to 50 events; use "
                           "`pio-tpu import` for bulk loads",
            })
            return
        # split by owner, preserving positions; malformed entries get
        # their 400 here (the worker would also 400 them, but a
        # routable batch must not be blocked by an unroutable entry)
        results: list[Optional[dict]] = [None] * len(items)
        groups: dict[int, list[int]] = {}  # worker index -> positions
        for k, item in enumerate(items):
            try:
                et = str(item["entityType"])
                ei = str(item["entityId"])
            except (TypeError, KeyError):
                results[k] = {
                    "status": 400,
                    "message": "event needs entityType and entityId",
                }
                continue
            six = _shard_ix(et, ei, self.config.n_shards)
            groups.setdefault(self.shard_owner[six].index, []).append(k)
        qs = urllib.parse.urlparse(path_qs).query
        suffix = f"?{qs}" if qs else ""
        by_index = {w.index: w for w in self.workers}
        any_down = False
        for windex, positions in sorted(groups.items()):
            w = by_index[windex]
            sub = [items[p] for p in positions]
            outcome = None
            if w.healthy:
                try:
                    status, data, _ = self._forward(
                        w, "POST", f"/batch/events.json{suffix}",
                        json.dumps(sub).encode(), trace_id=trace_id,
                    )
                    if status == 200:
                        outcome = json.loads(data.decode())
                    else:
                        # a whole-batch rejection (401 bad key, 400)
                        # applies to each event of the subset
                        msg = {}
                        try:
                            msg = json.loads(data.decode())
                        except ValueError:
                            pass
                        outcome = [{
                            "status": status,
                            "message": msg.get("message", ""),
                        }] * len(sub)
                except Exception:
                    outcome = None
            if outcome is None:
                any_down = True
                for p in positions:
                    six = _shard_ix(
                        str(items[p]["entityType"]),
                        str(items[p]["entityId"]),
                        self.config.n_shards,
                    )
                    self._book_unavailable(six)
                    results[p] = dict(
                        self._unavailable_payload(w, six), status=503,
                    )
                continue
            for p, r in zip(positions, outcome):
                results[p] = r
        hdrs = self._retry_hdr() if any_down else []
        if trace_id:
            hdrs = hdrs + [(TRACE_HEADER, trace_id)]
        self._respond_quiet(respond, 200, results, extra_headers=hdrs)
        self._offer_flight(
            trace_id, t0, events=len(items),
            workers=sorted(by_index[i].name for i in groups),
            status=200, anyDown=any_down or None,
        )

    def _post_webhook(self, path_qs: str, path: str, body: bytes,
                      respond, trace_id: Optional[str] = None) -> None:
        """Webhook ingestion under sharding: the CONNECTOR decides the
        entity, so the router must run it to learn the owner.  Convert
        here, then forward the derived event as a plain POST — the
        worker re-validates and authenticates as usual."""
        name = path[len("/webhooks/"):]
        try:
            if name.endswith(".json"):
                connector = JSON_CONNECTORS.get(name[: -len(".json")])
                data = json.loads(body.decode() or "{}")
            elif name.endswith(".form"):
                connector = FORM_CONNECTORS.get(name[: -len(".form")])
                form = urllib.parse.parse_qs(
                    body.decode(), keep_blank_values=True
                )
                data = {k: v[0] for k, v in form.items()}
            else:
                connector = None
            if connector is None:
                self._respond_quiet(
                    respond, 404, {"message": f"webhook {name} not found"}
                )
                return
            event = to_event(connector, data)
        except (ConnectorError, ValueError, UnicodeDecodeError) as e:
            self._respond_quiet(respond, 400, {"message": str(e)})
            return
        qs = urllib.parse.urlparse(path_qs).query
        suffix = f"?{qs}" if qs else ""
        self._post_event(
            f"/events.json{suffix}",
            json.dumps(event.to_json()).encode(),
            respond, trace_id=trace_id,
        )

    # -- read path (pool side) --------------------------------------------
    def _forward_read(self, method: str, path_qs: str, respond) -> None:
        """Reads prefer the entity's owner (its WAL barrier makes a
        just-acked write visible); keyspace-wide reads take any healthy
        worker.  Cross-owner read-your-writes is bounded by the owners'
        commit interval (~20ms), the documented federation caveat."""
        u = urllib.parse.urlparse(path_qs)
        params = urllib.parse.parse_qs(u.query)
        w = None
        et, ei = params.get("entityType"), params.get("entityId")
        if et and ei:
            w = self.owner_of(et[0], ei[0])
            if not w.healthy:
                six = _shard_ix(et[0], ei[0], self.config.n_shards)
                self._book_unavailable(six)
                self._respond_quiet(
                    respond, 503, self._unavailable_payload(w, six),
                    extra_headers=self._retry_hdr(),
                )
                return
        if w is None:
            w = self._any_healthy()
        if w is None:
            self._respond_quiet(
                respond, 503,
                {"message": "no ingest worker available",
                 "error": "NoWorkerAvailable"},
                extra_headers=self._retry_hdr(),
            )
            return
        try:
            status, data, ctype = self._forward(w, method, path_qs, None)
        except Exception as e:
            self._respond_quiet(
                respond, 503,
                {"message": f"worker {w.name} died mid-read: {e}",
                 "error": "NoWorkerAvailable"},
                extra_headers=self._retry_hdr(),
            )
            return
        self._respond_quiet(respond, status, data, ctype=ctype)

    # -- federation (pool side) -------------------------------------------
    def _get_stats(self, path_qs: str, respond) -> None:
        """Federated ``/stats.json``: every worker's payload merged;
        a dead worker contributes its last good payload so the merged
        counters never step backward (monotone-through-death, the same
        contract the /metrics federation proved in pio-lens)."""
        u = urllib.parse.urlparse(path_qs)
        cache_key = u.query
        payloads = []
        first_err: Optional[tuple[int, bytes, str]] = None
        for w in self.workers:
            got = None
            if w.healthy:
                try:
                    status, data, ctype = self._forward(
                        w, "GET", path_qs, None
                    )
                    if status == 200:
                        got = json.loads(data.decode())
                    elif first_err is None:
                        # auth/4xx propagates verbatim — a bad access
                        # key is the client's problem, not the fleet's
                        first_err = (status, data, ctype)
                except Exception:
                    got = None
            if got is not None:
                w.last_stats[cache_key] = got
                payloads.append(got)
            elif cache_key in w.last_stats:
                payloads.append(w.last_stats[cache_key])
        if not payloads:
            if first_err is not None:
                status, data, ctype = first_err
                self._respond_quiet(respond, status, data, ctype=ctype)
            else:
                self._respond_quiet(
                    respond, 503,
                    {"message": "no ingest worker answered /stats.json",
                     "error": "NoWorkerAvailable"},
                    extra_headers=self._retry_hdr(),
                )
            return
        merged = merge_stats_payloads(payloads)
        merged["workers"] = {
            "total": len(self.workers),
            "healthy": sum(w.healthy for w in self.workers),
            "reporting": len(payloads),
        }
        self._respond_quiet(respond, 200, merged)

    def render_fleet_metrics(self) -> bytes:
        """``GET /metrics``: router-local state merged with every
        worker's last scraped snapshot, gauges labeled ``{worker}`` —
        one scrape answers for the whole ingest fleet, and a dead
        worker's last-good snapshot keeps the merged counters
        monotone."""
        tagged = [("router", get_registry().dump_state())]
        for w in self.workers:
            if w.metrics_state is not None:
                tagged.append((w.name, w.metrics_state))
        try:
            return render_state(
                merge_states(tagged, gauge_label="worker")
            ).encode()
        except ValueError as e:
            logger.warning(
                "ingest fleet metrics merge failed (%s); serving the "
                "router-local exposition", e,
            )
            return get_registry().render_prometheus().encode()

    # -- status ------------------------------------------------------------
    def status_json(self) -> dict:
        out = {
            "status": "alive",
            "role": "ingest-router",
            "nShards": self.config.n_shards,
            "workers": [
                dict(w.snapshot(), shards=w.shards, index=w.index)
                for w in self.workers
            ],
            "healthyWorkers": sum(w.healthy for w in self.workers),
            "shardOwners": {
                str(s): w.name
                for s, w in sorted(self.shard_owner.items())
            },
            "requestCount": self.request_count,
            "shardUnavailable": self.shard_unavailable,
            "startTime": self.start_time,
        }
        fs = self.flight.summary()
        out["flight"] = {k: fs[k]
                         for k in ("capacity", "offers", "admissions")}
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.summary()
        return out

    # -- http --------------------------------------------------------------
    @staticmethod
    def _respond_quiet(respond, code, payload, ctype="application/json",
                       extra_headers=()) -> None:
        try:
            respond(code, payload, ctype=ctype,
                    extra_headers=list(extra_headers))
        except RuntimeError:
            pass  # client hung up first

    def _submit(self, respond, fn, *args) -> None:
        pool = self._pool
        if pool is None:
            self._respond_quiet(
                respond, 503, {"message": "ingest router is stopping"}
            )
            return

        def run():
            try:
                fn(*args)
            except Exception as e:
                logger.exception("ingest router handler failed")
                self._respond_quiet(respond, 500, {"message": str(e)})

        try:
            pool.submit(run)
        except RuntimeError:
            self._respond_quiet(
                respond, 503, {"message": "ingest router is stopping"}
            )

    @callback_scope
    def _el_handle(self, req, respond) -> None:
        u = urllib.parse.urlparse(req.path)
        path = u.path
        if req.method == "POST":
            self.request_count += 1  # loop-thread only: no lock needed
            # pio-lens discipline on the write edge too: mint a trace
            # id when the client didn't bring one, so every routed
            # write is flight-recordable and stitchable across the
            # router's and the shard owner's journals
            tid = (req.header(TRACE_HEADER) or "").strip() \
                or new_trace_id()
            if path == "/events.json":
                self._submit(respond, self._post_event,
                             req.path, req.body, respond, tid)
                return
            if path == "/batch/events.json":
                self._submit(respond, self._post_batch,
                             req.path, req.body, respond, tid)
                return
            if path.startswith("/webhooks/"):
                self._submit(respond, self._post_webhook,
                             req.path, path, req.body, respond, tid)
                return
            if path == "/stop":
                respond(200, {"message": "stopping"})
                threading.Thread(target=self.stop, daemon=True).start()
                return
            respond(404, {"message": "not found"})
            return
        if req.method == "GET":
            if path == "/metrics":
                if not metrics_enabled():
                    respond(404, {"message":
                                  "metrics disabled (--no-metrics)"})
                    return
                self._submit(respond, lambda: self._respond_quiet(
                    respond, 200, self.render_fleet_metrics(),
                    ctype=PROMETHEUS_CTYPE,
                ))
                return
            if path == "/stats.json":
                self._submit(respond, self._get_stats,
                             req.path, respond)
                return
            if path == "/":
                respond(200, self.status_json())
                return
            if (path == "/events.json"
                    or (path.startswith("/events/")
                        and path.endswith(".json"))
                    or path.startswith("/webhooks/")):
                self._submit(respond, self._forward_read,
                             "GET", req.path, respond)
                return
            ans = observability_response(path, u.query)
            if ans is not None:
                code, payload, ctype = ans
                respond(code, payload,
                        ctype=ctype or "application/json")
                return
        if req.method == "DELETE" and path.startswith("/events/"):
            # deletes fan to every shard file inside the worker; any
            # healthy worker can run one (sqlite arbitrates the writer
            # locks cross-process for this rare, non-hot-path op)
            self._submit(respond, self._forward_read,
                         "DELETE", req.path, respond)
            return
        respond(404, {"message": "not found"})


# -- worker process spawning -------------------------------------------------


def spawn_ingest_worker(index: int, n_workers: int, coord_dir,
                        wal_root=None, extra_args=(), env=None,
                        python: Optional[str] = None) -> dict:
    """Launch one shard-owner worker: ``pio-tpu eventserver`` on an
    ephemeral port with ``--owned-shards`` striped for ``index``,
    announcing through a port file (the `router.spawn_replica`
    protocol — pair with `router.wait_for_port_file`).  Storage config
    rides the environment (``PIO_STORAGE_*``); each worker's WAL lives
    under ``wal_root/worker-<index>`` so a respawn replays exactly its
    own acknowledged backlog."""
    import os as _os
    import subprocess
    import sys as _sys

    coord_dir = Path(coord_dir)
    coord_dir.mkdir(parents=True, exist_ok=True)
    port_file = coord_dir / f"worker-{index}.port"
    try:
        port_file.unlink()
    except FileNotFoundError:
        pass
    log_path = coord_dir / f"worker-{index}.log"
    wal_root = Path(wal_root) if wal_root else coord_dir / "wal"
    pkg_root = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(env if env is not None else _os.environ)
    pp = env.get("PYTHONPATH", "")
    if pkg_root not in pp.split(_os.pathsep):
        env["PYTHONPATH"] = pkg_root + (_os.pathsep + pp if pp else "")
    cmd = [
        python or _sys.executable, "-m", "predictionio_tpu.cli.main",
        "eventserver",
        "--ip", "127.0.0.1", "--port", "0",
        "--port-file", str(port_file),
        "--worker-index", str(index),
        "--worker-count", str(n_workers),
        "--wal-dir", str(wal_root / f"worker-{index}"),
        *extra_args,
    ]
    log_f = open(log_path, "w")
    proc = subprocess.Popen(
        cmd, stdout=log_f, stderr=subprocess.STDOUT, env=env,
    )
    log_f.close()
    return {"proc": proc, "port_file": port_file,
            "log_path": log_path, "index": index}


def boot_ingest_fleet(n_workers: int, n_shards: int, coord_dir,
                      config: Optional[IngestRouterConfig] = None,
                      wal_root=None, extra_args=(), env=None,
                      spawn_timeout_s: float = 180.0,
                      respawn: bool = True,
                      ) -> tuple[IngestRouterServer, list[dict]]:
    """Spawn ``n_workers`` shard-owner processes, wait for their port
    announcements, and return a wired (not yet bound) router plus the
    spawned dicts.  ``respawn`` attaches the supervisor so a killed
    worker comes back on its own."""
    spawned = [
        spawn_ingest_worker(
            i, n_workers, coord_dir,
            wal_root=wal_root, extra_args=extra_args, env=env,
        )
        for i in range(n_workers)
    ]
    workers = []
    for s in spawned:
        port = wait_for_port_file(s, timeout_s=spawn_timeout_s)
        workers.append(IngestWorker(
            f"worker-{s['index']}", "127.0.0.1", port,
            shards_for_worker(s["index"], n_workers, n_shards),
            s["index"],
        ))
    supervisor = None
    if respawn:
        supervisor = ReplicaSupervisor(
            spawner=lambda i: spawn_ingest_worker(
                i, n_workers, coord_dir,
                wal_root=wal_root, extra_args=extra_args, env=env,
            ),
            spawn_timeout_s=spawn_timeout_s,
        )
        for w, s in zip(workers, spawned):
            supervisor.attach(w, s)
    cfg = config or IngestRouterConfig(n_shards=n_shards)
    cfg.n_shards = n_shards
    return IngestRouterServer(workers, cfg, supervisor), spawned
