"""Engine deployment server: answers ``/queries.json`` with predictions.

Re-expression of reference `workflow/CreateServer.scala` (`ServerActor`
routes `:433-612`, `MasterActor` lifecycle `:255-377`) on the stdlib
threading HTTP server — no spray/akka.  Routes:

* ``GET  /``             — status JSON: engine info, request count, latency
  (``avgServingSec``/``lastServingSec`` parity, `CreateServer.scala:552-559`)
* ``POST /queries.json`` — score a query (the hot path)
* ``GET  /reload``       — hot-swap to the latest COMPLETED engine instance
  without restarting the process (`:315-336,592-599`)
* ``POST /stop``         — graceful shutdown (`:600-607`)

Query/result JSON mapping: the engine's first algorithm may declare
``query_class`` (with ``from_json``) and results may expose ``to_json`` —
the serving-layer analogue of the reference's json4s ``Extraction.extract``
(`:470-471`).  Scoring runs a precompiled batched XLA call per request;
feedback-loop event injection (prId) is wired when an event server URL is
configured.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
import uuid
from dataclasses import is_dataclass, asdict
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Optional

from ..controller.base import WorkflowContext
from .http_base import (
    HTTPServerBase,
    JsonRequestHandler,
    observability_response,
)
from .eventloop import callback_scope
from .microbatch import AdmissionRejected
from ..controller.engine import Engine, EngineParams
from ..obs import (
    ENGINE_QUERIES_TOTAL,
    FOLDIN_APPLIES_TOTAL,
    FOLDIN_PHASE_SECONDS,
    FOLDIN_WATERMARK_LAG,
    MODEL_FRESHNESS_SECONDS,
    QUERIES_TOTAL,
    QUERY_LATENCY,
    RELOADS_TOTAL,
    TRACE_HEADER,
    Histogram,
    current_trace_id,
    get_flight_recorder,
    get_tracer,
    new_trace_id,
    scope,
    timeline,
    trace_scope,
    xray,
)
from ..obs.timeline import SERVE_INFLIGHT, annotate
from ..resilience import faults
from ..resilience.delivery import DeliveryQueue
from ..resilience.policy import (
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    deadline_scope,
)
from ..tenancy.errors import QuotaExceeded, TenantUnavailable
from ..workflow.train import prepare_deploy_components

logger = logging.getLogger(__name__)

__all__ = ["EngineServer", "ServerConfig"]

# pulse: the serving edge's saturation gauge, child cached at import
# (labels()/child() lookups are too hot for the per-request path)
_m_inflight = SERVE_INFLIGHT.child()


class ServerConfig:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 feedback: bool = False, event_server_url: Optional[str] = None,
                 access_key: Optional[str] = None,
                 log_url: Optional[str] = None, log_prefix: str = "",
                 microbatch: str = "auto", microbatch_max: int = 64,
                 shared_batcher: bool = True,
                 query_timeout_s: Optional[float] = None,
                 feedback_capacity: int = 1024,
                 delivery_attempts: int = 50,
                 delivery_base_s: float = 0.1,
                 delivery_cap_s: float = 5.0,
                 delivery_timeout_s: float = 2.0,
                 breaker_failures: int = 5,
                 breaker_reset_s: float = 10.0,
                 retry_seed: Optional[int] = None,
                 foldin_poll_s: Optional[float] = None,
                 edge: str = "eventloop",
                 max_connections: int = 512,
                 slo_ms: Optional[float] = None):
        self.host = host
        self.port = port
        # pio-surge: which HTTP front end answers the port.
        # "eventloop" (default) = ONE selector loop thread parses and
        # routes every connection, device work rides the micro-batcher
        # dispatcher, blocking routes ride a small aux pool — no thread
        # per connection.  "threads" = the pre-surge stdlib
        # ThreadingHTTPServer edge (kept for bitwise-compatible A/B
        # benchmarking and as a fallback).
        if edge not in ("eventloop", "threads"):
            raise ValueError(f"edge must be eventloop|threads, got {edge!r}")
        self.edge = edge
        # concurrent-connection cap (both edges): connection attempts
        # past it are answered a structured 503 and closed, so a
        # slow-loris client can't pin unbounded threads/sockets
        self.max_connections = max_connections
        self.feedback = feedback
        self.event_server_url = event_server_url
        self.access_key = access_key
        # remote error-log shipping (CreateServer.scala:413-424): serving
        # failures POST `log_prefix + json` to log_url, fire-and-forget
        self.log_url = log_url
        self.log_prefix = log_prefix
        # concurrent-query coalescing (server/microbatch.py): "auto"
        # batches when every algorithm provides a real batch_predict,
        # "on" forces it, "off" keeps per-request device dispatch
        self.microbatch = microbatch
        self.microbatch_max = microbatch_max
        # pio-confluence: ONE shared continuous batcher per server —
        # every tenant submits into a single pending queue whose
        # dispatcher claims via weighted deficit round-robin across
        # tenants, so cross-tenant concurrency coalesces onto the
        # device instead of competing per-tenant dispatchers.  Off =
        # the pre-confluence private-batcher-per-tenant layout (kept
        # for A/B benchmarking and as an operator escape hatch).
        self.shared_batcher = shared_batcher
        # per-request time budget (None = unbounded, the pre-resilience
        # behavior); expiry answers a structured 503 instead of queueing
        # device work for a client that already gave up
        self.query_timeout_s = query_timeout_s
        # feedback/remote-log delivery queue + breaker knobs
        self.feedback_capacity = feedback_capacity
        self.delivery_attempts = delivery_attempts
        self.delivery_base_s = delivery_base_s
        self.delivery_cap_s = delivery_cap_s
        self.delivery_timeout_s = delivery_timeout_s
        self.breaker_failures = breaker_failures
        self.breaker_reset_s = breaker_reset_s
        self.retry_seed = retry_seed
        # pio-live: poll the model dir for fold-in delta links every N
        # seconds and patch them into the serving model in place (no
        # stop-the-world reload).  None = off; deltas already on disk
        # at (re)load time are still caught up once.
        self.foldin_poll_s = foldin_poll_s
        # pio-lens: latency SLO in milliseconds — arms the
        # pio_slo_burn_rate{window} gauges on this server's end-to-end
        # latency histogram (None = no SLO, gauges stay absent)
        self.slo_ms = slo_ms


class _QueryCtx:
    """Per-query snapshot shared by the blocking and event-loop paths:
    decoded query, deadline, the components captured under the state
    lock, the pio-live attribution fields, and (pio-hive) the tenant
    lease the query holds."""

    __slots__ = ("query", "deadline", "algorithms", "models", "serving",
                 "batcher", "freshness", "foldin_seq", "lease")

    def __init__(self, query, deadline, algorithms, models, serving,
                 batcher, freshness, foldin_seq, lease=None):
        self.query = query
        self.deadline = deadline
        self.algorithms = algorithms
        self.models = models
        self.serving = serving
        self.batcher = batcher
        self.freshness = freshness
        self.foldin_seq = foldin_seq
        self.lease = lease


def _lease_status(e: BaseException) -> str:
    """Map a query-path exception to the per-tenant outcome label (the
    same taxonomy the HTTP error mapping uses)."""
    if isinstance(e, QuotaExceeded):
        return "quota"
    if isinstance(e, TenantUnavailable):
        return "shed"
    if isinstance(e, AdmissionRejected):
        return "rejected"
    if isinstance(e, DeadlineExceeded):
        return "timeout"
    if isinstance(e, (KeyError, ValueError, TypeError)):
        return "bad_request"
    return "error"


def _takes_max_batch(fn: Callable) -> bool:
    """Whether a warmup hook accepts the ``max_batch`` keyword (older
    third-party algorithms may still have the one-arg signature).
    Hooks taking ``**kwargs`` (or whose visible signature is erased by
    a plain decorator) count as accepting it."""
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return "max_batch" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )


def _warm_signature(algo, model, warm_max: int) -> Optional[tuple]:
    """Shape signature of one (algorithm, model) warmup obligation —
    two tenants with equal signatures compile the SAME pow2 executable
    ladder (jit caches key on function identity + abstract shapes), so
    the second tenant's full-ladder warmup would be pure cache hits.
    None (unrecognizable model) means "never share"."""
    try:
        fields = vars(model)
    except TypeError:
        return None
    shapes = []
    for name in sorted(fields):
        v = fields[name]
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is not None and dtype is not None:
            shapes.append((name, tuple(shape), str(dtype)))
    if not shapes:
        return None
    try:
        params_repr = repr(getattr(algo, "params", None))
    except Exception:
        params_repr = "?"
    return (type(algo).__module__, type(algo).__qualname__,
            params_repr, warm_max, tuple(shapes))


def _warm_components(algorithms, models, warm_max: int,
                     seen: Optional[set] = None) -> None:
    """Run each algorithm's warmup ladder (shared by the engine
    server's own ``_load`` and the pio-hive tenant loader — a lazily
    loaded tenant gets the exact same compile obligations a deployed
    single model does).  A warmup failure only costs the first query a
    compile; it never fails the load.

    ``seen`` (pio-confluence) shares the ladder across co-shaped
    tenants: the FIRST (algo, model) with a given shape signature
    warms the full pow2 ladder; later co-shaped ones warm only
    ``max_batch=1`` — enough to materialize their own per-model device
    arrays, while every batched executable comes out of the jit cache
    the first tenant already filled.  A concurrent double-warm is a
    benign race (both warm fully), so ``seen`` needs no lock."""
    for algo, model in zip(algorithms, models):
        algo_max = warm_max
        sig = _warm_signature(algo, model, warm_max) if seen is not None \
            else None
        if sig is not None and sig in seen:
            algo_max = 1
        t0 = time.perf_counter()
        try:
            # pass the batcher's real maximum so the warmup ladder
            # covers every pow2 size the padding can dispatch; algos
            # with the pre-max_batch one-arg signature still work
            if _takes_max_batch(algo.warmup):
                try:
                    algo.warmup(model, max_batch=algo_max)
                except TypeError:
                    # a decorator-erased signature (*args/**kwargs
                    # wrapper around an old one-arg hook) can lie
                    # about accepting max_batch; retry plain once
                    # rather than regress a hook that warmed fine
                    # before max_batch existed
                    algo.warmup(model)
            else:
                algo.warmup(model)
        except Exception:
            logger.exception(
                "warmup failed for %s (first query will compile)",
                type(algo).__name__,
            )
        else:
            if sig is not None:
                seen.add(sig)
            dt = time.perf_counter() - t0
            if dt > 0.05:
                logger.info("%s warmed up in %.2fs",
                            type(algo).__name__, dt)


def _default_query_decoder(engine: Engine, engine_params: EngineParams):
    name, _ = engine_params.algorithms[0]
    cls = engine._lookup(engine.algorithm_class_map, name, "algorithm")
    qcls = getattr(cls, "query_class", None)
    if qcls is None:
        # try the template convention: module-level Query with from_json
        import sys

        mod = sys.modules.get(cls.__module__)
        qcls = getattr(mod, "Query", None) if mod else None
    if qcls is not None and hasattr(qcls, "from_json"):
        return qcls.from_json
    if qcls is not None and is_dataclass(qcls):
        # plain dataclass Query without from_json: construct it from the
        # matching JSON fields (the generic analogue of the reference's
        # json4s ``Extraction.extract`` into case classes,
        # `CreateServer.scala:470-471`); unknown keys are ignored
        import dataclasses

        names = {f.name for f in dataclasses.fields(qcls)}

        def decode(d):
            return qcls(**{k: v for k, v in d.items() if k in names})

        return decode
    return lambda d: d


def _result_to_json(r: Any) -> Any:
    if hasattr(r, "to_json"):
        return r.to_json()
    if is_dataclass(r) and not isinstance(r, type):
        return asdict(r)
    if isinstance(r, (list, tuple)):
        return [_result_to_json(v) for v in r]
    if isinstance(r, dict):
        return {k: _result_to_json(v) for k, v in r.items()}
    return r


def _experiments_response(tenants) -> tuple:
    """``GET /debug/experiments``: the autopilot's live document, a
    disabled stub when tenancy runs without an autopilot, 404 when
    there is no tenancy at all.  Returns ``(code, payload)``."""
    if tenants is None:
        from ..tenancy.autopilot import autopilot_payload

        doc = autopilot_payload()
        if doc is not None:
            return 200, doc
        return 404, {"message": "tenancy is not enabled (deploy --multi)"}
    pilot = getattr(tenants, "autopilot", None)
    if pilot is not None:
        return 200, pilot.payload()
    return 200, {
        "enabled": False,
        "weights": {
            app: tenants.experiment(app).weights()
            for app in tenants.apps()
        },
        "onlineEval": tenants.online.snapshot(),
    }


class EngineServer(HTTPServerBase):
    """One deployed engine instance behind an HTTP server."""

    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        instance_id: str,
        ctx: Optional[WorkflowContext] = None,
        config: Optional[ServerConfig] = None,
        query_decoder: Optional[Callable[[dict], Any]] = None,
        engine_id: str = "default",
        engine_version: str = "1",
        engine_variant: str = "engine.json",
        tenants=None,
    ):
        self.engine = engine
        self.engine_params = engine_params
        self.ctx = ctx or WorkflowContext(mode="Serving")
        self.config = config or ServerConfig()
        self.instance_id = instance_id
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        # pio-hive: an optional TenantRegistry turns this server into a
        # multi-tenant host — queries carrying app/appId/accessKey (+
        # optional variant) route to the registry's resident models,
        # everything else rides the anchor components loaded below.
        # The registry gets this server's component loader unless the
        # caller injected its own (benches/tests pass prebuilt models).
        self.tenants = tenants
        if tenants is not None and tenants.loader is None:
            tenants.loader = self._tenant_loader
        self.query_decoder = query_decoder or _default_query_decoder(
            engine, engine_params
        )
        self._lock = threading.RLock()
        self.last_reload_error: Optional[str] = None
        # bounded background delivery (resilience/delivery.py) replaces
        # the old thread-per-request fire-and-forget POSTs; built even
        # when feedback/log_url are off (the drain thread only starts on
        # first submit) so post-init config changes keep working
        def _queue(name, point):
            return DeliveryQueue(
                name,
                capacity=self.config.feedback_capacity,
                retry=RetryPolicy(
                    max_attempts=self.config.delivery_attempts,
                    base_s=self.config.delivery_base_s,
                    cap_s=self.config.delivery_cap_s,
                    seed=self.config.retry_seed,
                ),
                breaker=CircuitBreaker(
                    failure_threshold=self.config.breaker_failures,
                    reset_timeout_s=self.config.breaker_reset_s,
                ),
                timeout_s=self.config.delivery_timeout_s,
                fault_point=point,
            )

        self._feedback_queue = _queue("feedback", "http.feedback")
        self._log_queue = _queue("remote-log", "http.remote_log")
        # pio-live delta-poll machinery, built before the first _load
        # (which catches up on any chain already on disk): repeated
        # apply failures open the breaker — polling pauses, the stale
        # model keeps serving, exactly the failed-/reload semantics
        self._foldin_breaker = CircuitBreaker(
            failure_threshold=self.config.breaker_failures,
            reset_timeout_s=self.config.breaker_reset_s,
        )
        # pio-surge admission breaker: consecutive deadline-admission
        # rejects open it, and while open every deadlined request is
        # shed immediately (no estimator math) — the cheap-shed mode an
        # overloaded edge needs; any completed query closes it again
        self._admission_breaker = CircuitBreaker(
            failure_threshold=max(self.config.breaker_failures * 4, 8),
            reset_timeout_s=min(self.config.breaker_reset_s, 1.0),
        )
        # aux pool for the event-loop edge's blocking routes (status,
        # reload, /debug/profile, fold-in apply, unbatched predicts);
        # built lazily at first bind of the eventloop edge
        self._aux_pool = None
        # pio-confluence: the process-wide shared batcher core (built
        # lazily by the first _make_batcher call that wants one) plus
        # the warmup-ladder signature set — co-shaped tenant models
        # share one compile per pow2 batch shape instead of re-warming
        # the full ladder per tenant
        self._shared_core = None
        self._shared_lock = threading.Lock()
        self._warm_signatures: set = set()
        self._foldin_stop = threading.Event()
        self._load(instance_id)
        if self.config.foldin_poll_s:
            threading.Thread(
                target=self._foldin_poll_loop,
                daemon=True,
                name="foldin-poll",
            ).start()
        # pio-hive: the online-eval poller folds variant-attributed
        # conversion events back out of the event store on a cadence
        self._eval_stop = threading.Event()
        if self.tenants is not None:
            threading.Thread(
                target=self._online_eval_loop,
                daemon=True,
                name="hive-eval",
            ).start()
        # serving stats (CreateServer.scala:396-398).  Latency is
        # histogram-backed (pio-obs): this instance's private histogram
        # drives the /status percentiles + average, and the same deltas
        # feed the process-wide pio_query_latency_seconds family that
        # /metrics exposes — one measurement, two views.
        self.request_count = 0
        self.last_serving_sec = 0.0
        self.start_time = time.time()  # wall clock: a TIMESTAMP, not a span
        self._latency = Histogram()
        self._m_latency = QUERY_LATENCY.child()
        # per-outcome query counters resolved once (.labels() is too
        # hot for per-request use); shared by both edges
        self._m_queries = {
            s: QUERIES_TOTAL.labels(status=s)
            for s in ("ok", "bad_request", "timeout", "error", "rejected")
        }
        # pio-forge: the engine-labeled mirror — every query books
        # {engine=<registered spec name>} so multi-engine fleets (and
        # the conformance suite) read per-engine traffic off /metrics
        from ..engines import engine_label_of

        self.engine_name = engine_label_of(engine, fallback=engine_id)
        self._m_engine_queries = {
            s: ENGINE_QUERIES_TOTAL.labels(engine=self.engine_name,
                                           status=s)
            for s in ("ok", "bad_request", "timeout", "error",
                      "rejected", "quota", "shed")
        }
        self._httpd: Optional[ThreadingHTTPServer] = None
        # pio-lens: --slo-ms arms the error-budget burn-rate gauges on
        # the process-wide latency histogram (the replica-side half of
        # the fleet's alert-ready signal; the router arms its own on
        # the forward round-trip histogram)
        self._burn = None
        if self.config.slo_ms:
            from ..obs import fleet

            self._burn = fleet.install_burn_rate(
                self._m_latency, self.config.slo_ms / 1e3
            )
        # pio-xray: compile/cache events during warmup+serving book into
        # /metrics, and the daemon device sampler keeps the per-device
        # memory gauges fresh (registered like the breaker gauges above)
        xray.install()
        xray.start_sampler()
        # pio-scope: the always-on CPU sampler rides every serving
        # process (no-op when --no-profiler / PIO_TPU_SCOPE=0 opted out)
        scope.ensure_started()

    # -- lifecycle --------------------------------------------------------
    def _load(self, instance_id: str) -> None:
        # a failed (re)load must leave the previous components serving —
        # nothing below mutates server state until the atomic swap at
        # the end, and the injection point lets chaos tests prove it
        faults.check("reload.load_model")
        # serve with the params the instance was trained with; the current
        # engine.json may have drifted (engineInstanceToEngineParams parity)
        with self._lock:
            variant_params = self.engine_params
        engine_params = variant_params
        rec = self.ctx.storage.get_metadata().engine_instance_get(instance_id)
        if rec is not None and rec.algorithms_params:
            try:
                engine_params = self.engine.params_from_instance(rec)
            except Exception:
                logger.exception(
                    "could not reconstruct params from instance %s; "
                    "using variant params", instance_id,
                )
                engine_params = variant_params
        algorithms, models, serving = prepare_deploy_components(
            self.engine, engine_params, instance_id, ctx=self.ctx
        )
        # the batcher decides which batch sizes serving can dispatch, so
        # build it BEFORE warmup: with batching off (or auto-gated off)
        # every request runs B=1 and compiling the batched ladder at
        # deploy/reload time would be pure wasted XLA work
        batcher = self._make_batcher(algorithms, models)
        # 0 = "no batched path at all" (empty warmup ladder); a real
        # batcher with microbatch_max=1 still needs its B=1 shapes
        warm_max = self.config.microbatch_max if batcher is not None else 0
        _warm_components(algorithms, models, warm_max,
                         seen=self._warm_signatures)
        with self._lock:
            old_batcher = getattr(self, "batcher", None)
            self.engine_params = engine_params
            self.models = models
            self.algorithms = algorithms
            self.serving = serving
            self.instance_id = instance_id
            self.batcher = batcher
            # pio-live bookkeeping restarts with every full (re)load:
            # the delta chain is per instance, and a fresh full model
            # IS the freshness anchor
            self.foldin_applied_seq = {}
            self.foldin_watermark = None
            self.foldin_deltas_applied = 0
            self.last_foldin_error = None
            self.model_advanced_mono = time.monotonic()
        # the old batcher's dispatcher thread (continuous path) drains
        # and exits; in-flight queries still holding it complete fine
        if old_batcher is not None and old_batcher is not batcher:
            old_batcher.close()
        # catch up on delta links already published for this instance
        # (a redeploy/reload must not serve staler than the chain)
        self._apply_available_deltas()
        # pio-hive: re-adopt the freshly loaded components as the
        # anchor tenant's runtime (ONE model copy serves both the
        # tenant-less default path and explicit anchor queries; a
        # /reload therefore advances the anchor tenant too)
        if getattr(self, "tenants", None) is not None:
            self._adopt_anchor_runtime()

    # -- pio-hive: tenant component loading --------------------------------
    def _tenant_breaker(self) -> CircuitBreaker:
        return CircuitBreaker(
            failure_threshold=self.config.breaker_failures,
            reset_timeout_s=self.config.breaker_reset_s,
        )

    def _tenant_quota(self, spec):
        from ..tenancy.quota import TokenBucket

        if spec.quota_qps is None:
            return None
        return TokenBucket(spec.quota_qps, spec.quota_burst)

    def _adopt_anchor_runtime(self) -> None:
        from ..tenancy.registry import TenantRuntime

        spec = self.tenants.spec(self.tenants.anchor_key)
        with self._lock:
            rt = TenantRuntime(
                spec, self.engine, self.engine_params, self.instance_id,
                self.algorithms, self.models, self.serving, self.batcher,
                self.query_decoder, self.ctx,
                breaker=self._tenant_breaker(),
                quota=self._tenant_quota(spec),
            )
        self.tenants.adopt_anchor(rt)

    def _resolve_tenant_components(self, spec):
        """(engine, engine_params, instance_id, ctx) for a spec —
        prebuilt objects win, then a registered engine name (pio-forge
        registry dispatch), else the engine.json is loaded; either way
        the latest COMPLETED instance resolves exactly like
        ``deploy``."""
        ctx = spec.ctx or self.ctx
        if spec.engine is not None:
            if spec.instance_id is None:
                raise ValueError(
                    f"tenant {spec.key_str}: a prebuilt engine needs an "
                    "instance_id"
                )
            return spec.engine, spec.engine_params, spec.instance_id, ctx
        if spec.engine_name:
            from .. import engines

            engine, ep, variant = engines.resolve(spec.engine_name)
            variant_key = f"engine:{spec.engine_name}"
        else:
            from ..cli.main import load_engine_from_variant

            engine, ep, variant = load_engine_from_variant(
                spec.engine_json
            )
            variant_key = str(spec.engine_json)
        iid = spec.instance_id
        if iid is None:
            md = ctx.storage.get_metadata()
            latest = md.engine_instance_get_latest_completed(
                variant.get("id", "default"), "1", variant_key
            )
            if latest is None:
                raise LookupError(
                    f"tenant {spec.key_str}: no completed engine "
                    f"instance for {variant_key}; train it first"
                )
            iid = latest.id
        return engine, ep, iid, ctx

    def _tenant_loader(self, spec):
        """Build one tenant's full serving runtime — the same component
        pipeline ``_load`` runs for the anchor: prepare + batcher +
        warmup ladder + decoder, plus the per-tenant breaker/quota."""
        from ..tenancy.registry import TenantRuntime

        engine, ep, iid, ctx = self._resolve_tenant_components(spec)
        algorithms, models, serving = prepare_deploy_components(
            engine, ep, iid, ctx=ctx
        )
        batcher = self._make_batcher(algorithms, models, tenant=spec.key)
        warm_max = self.config.microbatch_max if batcher is not None else 0
        _warm_components(algorithms, models, warm_max,
                         seen=self._warm_signatures)
        return TenantRuntime(
            spec, engine, ep, iid, algorithms, models, serving, batcher,
            _default_query_decoder(engine, ep), ctx,
            breaker=self._tenant_breaker(),
            quota=self._tenant_quota(spec),
        )

    def _online_eval_loop(self) -> None:
        scope.register_thread_role("hive_eval")
        interval = max(float(self.tenants.eval_interval_s), 0.5)
        while not self._eval_stop.wait(interval):
            try:
                self.tenants.refresh_online_eval(
                    self.ctx.storage.get_event_store()
                )
            except Exception:
                logger.exception("online-eval refresh failed")
            # pio-pilot: the autopilot rides the same cadence — fresh
            # conversion counts in, at most one bounded ramp step out
            # (tick() never raises; a no-autopilot registry no-ops)
            try:
                self.tenants.autopilot_tick()
            except Exception:
                logger.exception("autopilot tick failed")

    def _make_batcher(self, algorithms, models, tenant=None):
        """Build the query micro-batcher for this (algorithms, models)
        snapshot — or None when batching can't help.

        Concurrent requests each dispatching their own device call
        serialize on the single TPU execution queue (measured:
        per-request latency grows ~linearly with thread count at flat
        QPS).  When every algorithm overrides ``batch_predict`` with a
        real batched implementation, coalescing the in-flight queries
        into one [B]-wide device call makes concurrency wider instead
        of deeper — see server/microbatch.py.  The base-class
        ``batch_predict`` just maps ``predict``, which would serialize
        *inside* the leader's batch for no gain, so "auto" only
        batches genuinely batched algorithms.
        """
        from ..controller.base import Algorithm
        from .microbatch import MicroBatcher

        mode = self.config.microbatch
        if mode == "off":
            return None
        if mode == "auto" and not all(
            type(a).batch_predict is not Algorithm.batch_predict
            for a in algorithms
        ):
            return None

        def batch_fn(queries):
            if len(queries) == 1:
                # solo batches ride the scalar predict path: the [1, M]
                # batched executable is measurably SLOWER than the [M]
                # matvec one (CPU: 5.2 ms vs 1.5 ms at M=100k, R=64 —
                # a batched row top-k pays layout overhead a vector
                # top-k doesn't), and under no concurrency every batch
                # is solo
                q = queries[0]
                return [[
                    algo.predict(model, q)
                    for algo, model in zip(algorithms, models)
                ]]
            per_algo = [
                algo.batch_predict(model, queries)
                for algo, model in zip(algorithms, models)
            ]
            return [
                [pa[i] for pa in per_algo] for i in range(len(queries))
            ]

        # pad_batches: predicts are pure per-item maps, and padding
        # bounds the per-batch-size XLA executables to log2(max)+1
        # instead of compiling mid-traffic for every new size
        if not self.config.shared_batcher:
            return MicroBatcher(
                batch_fn, max_batch=self.config.microbatch_max,
                pad_batches=True,
            )
        # pio-confluence: every tenant (and the anchor) gets a VIEW on
        # one process-wide SharedBatcher — single pending queue, single
        # dispatcher, claim-time weighted deficit round-robin across
        # tenants.  The view carries this snapshot's batch_fn, so
        # entries group by model identity inside a claim and in-flight
        # queries survive a reload on the model they snapshotted.
        from .microbatch import SharedBatcher, SharedBatcherView

        with self._shared_lock:
            if self._shared_core is None:
                self._shared_core = SharedBatcher(
                    max_batch=self.config.microbatch_max,
                    pad_batches=True,
                )
            core = self._shared_core
        if tenant is None:
            tenants = getattr(self, "tenants", None)
            tenant = tenants.anchor_key if tenants is not None \
                else "__anchor__"
        weight_fn = None
        if self.tenants is not None:
            registry, key = self.tenants, tenant

            def weight_fn():
                # pulled at claim time: a hot POST /tenants/weights
                # reshapes the very next dispatcher claim
                return registry.deficit_weight(key)

        return SharedBatcherView(core, tenant, batch_fn,
                                 weight_fn=weight_fn)

    def reload(self) -> str:
        """Swap in the latest COMPLETED instance (GET /reload).

        A failed load is recorded (``lastReloadError`` in the status
        JSON) and re-raised; the previously-loaded components keep
        serving untouched — stale answers beat no answers."""
        md = self.ctx.storage.get_metadata()
        latest = md.engine_instance_get_latest_completed(
            self.engine_id, self.engine_version, self.engine_variant
        )
        if latest is None:
            raise LookupError("no completed engine instance found")
        with get_tracer().span("serve.reload",
                               attrs={"instance": latest.id}):
            try:
                self._load(latest.id)
            except Exception as e:
                with self._lock:
                    self.last_reload_error = f"{type(e).__name__}: {e}"
                RELOADS_TOTAL.labels(result="error").inc()
                raise
        with self._lock:
            self.last_reload_error = None
        RELOADS_TOTAL.labels(result="ok").inc()
        return latest.id

    # -- pio-live delta apply ---------------------------------------------
    def _apply_available_deltas(self) -> int:
        """Apply any fold-in delta links (pio-live) newer than what this
        server already holds, IN PLACE under the state lock — factor
        rows and the device-resident top-k index are patched row-wise;
        queries in flight keep scoring on the tables they snapshotted,
        the next query sees the folded-in rows.  No ``reload()``, no
        warmup, no batcher rebuild: the model OBJECTS stay the same,
        only their row contents advance.

        A torn or gapped chain truncates cleanly (``load_model_delta_
        chain``): the good prefix applies, the rest waits — stale rows
        beat corrupted rows.  Returns the number of links applied."""
        from ..live.apply import apply_model_delta, model_supports_deltas
        from ..workflow.model_io import load_model_delta_chain, model_key

        with self._lock:
            iid = self.instance_id
            models = self.models
            ep = self.engine_params
            applied_seq = dict(self.foldin_applied_seq)
        base_dir = self.ctx.storage.model_data_dir() / iid
        names = [n for n, _ in ep.algorithms]
        n_applied = 0
        for ax, (name, model) in enumerate(zip(names, models)):
            if not model_supports_deltas(model):
                continue
            key = model_key(iid, ax, name)
            chain, err = load_model_delta_chain(
                base_dir, key, after_seq=applied_seq.get(key, 0)
            )
            if err:
                with self._lock:
                    self.last_foldin_error = err
                logger.warning("fold-in chain for %s: %s", key, err)
            for d in chain:
                t0 = time.perf_counter()
                with self._lock:
                    if self.instance_id != iid:
                        # a reload swapped instances mid-walk; the new
                        # instance's own catch-up already ran
                        return n_applied
                    apply_model_delta(model, d)
                    self.foldin_applied_seq[key] = d.seq
                    self.foldin_watermark = d.watermark
                    self.foldin_deltas_applied += 1
                    self.model_advanced_mono = time.monotonic()
                    self.last_foldin_error = None
                dt = time.perf_counter() - t0
                FOLDIN_APPLIES_TOTAL.labels(result="ok").inc()
                FOLDIN_PHASE_SECONDS.labels(phase="live.apply").observe(dt)
                get_tracer().record(
                    "live.apply", dt,
                    attrs={"instance": iid, "seq": d.seq},
                )
                n_applied += 1
        return n_applied

    def _foldin_poll_loop(self) -> None:
        """Delta-poll daemon thread (``--foldin-poll``): breaker-guarded
        and deadline-scoped so a sick storage volume degrades to a
        paused poll + stale model, never a wedged serving thread."""
        scope.register_thread_role("foldin_runner")
        interval = float(self.config.foldin_poll_s)
        while not self._foldin_stop.wait(interval):
            if not self._foldin_breaker.allow():
                continue
            try:
                with deadline_scope(Deadline.after(max(interval, 1.0))):
                    self._apply_available_deltas()
                    if self.tenants is not None:
                        # per-tenant chains; one tenant's error is
                        # booked on that tenant inside the registry and
                        # never pauses the others (the fold-in half of
                        # the isolation contract)
                        self.tenants.apply_available_deltas()
            except Exception as e:
                logger.exception(
                    "fold-in delta apply failed; serving keeps the "
                    "stale model"
                )
                with self._lock:
                    self.last_foldin_error = f"{type(e).__name__}: {e}"
                FOLDIN_APPLIES_TOTAL.labels(result="error").inc()
                self._foldin_breaker.record_failure()
            else:
                self._foldin_breaker.record_success()
            self._refresh_foldin_gauges()

    def _foldin_status(self) -> dict:
        """The pio-live status fields, or {} while the subsystem is off
        (no poll configured and no delta ever applied) — status JSON
        stays byte-compatible for deployments that never fold in."""
        with self._lock:
            active = (
                self.config.foldin_poll_s is not None
                or self.foldin_deltas_applied > 0
                # a torn/gapped chain with zero applies must still
                # surface: the operator is one lastFoldinError away
                # from knowing why the model is stale
                or self.last_foldin_error is not None
            )
            if not active:
                return {}
            advanced_mono = self.model_advanced_mono
            wm = self.foldin_watermark
            err = self.last_foldin_error
            applied = self.foldin_deltas_applied
        freshness = max(time.monotonic() - advanced_mono, 0.0)
        lag = 0
        if wm:
            try:
                es = self.ctx.storage.get_event_store()
                if hasattr(es, "cursor_lag"):
                    # handles both cursor kinds (int rowid / sharded
                    # per-shard vector string) in the store itself
                    lag = max(es.cursor_lag(
                        int(wm.get("appId", -1)),
                        int(wm.get("channelId", 0)),
                        wm.get("rowid", 0),
                    ), 0)
                elif hasattr(es, "max_rowid"):
                    lag = max(
                        es.max_rowid(
                            int(wm.get("appId", -1)),
                            int(wm.get("channelId", 0)),
                        ) - int(wm.get("rowid", 0)),
                        0,
                    )
            except Exception:
                lag = 0
        out = {
            "modelFreshnessSec": freshness,
            "foldinWatermarkLag": lag,
            "foldinDeltasApplied": applied,
            "foldinBreakerState": self._foldin_breaker.state,
        }
        if err:
            out["lastFoldinError"] = err
        MODEL_FRESHNESS_SECONDS.child().set(freshness)
        FOLDIN_WATERMARK_LAG.child().set(float(lag))
        return out

    def _refresh_foldin_gauges(self) -> None:
        self._foldin_status()  # computing the fields also sets the gauges

    # -- query path -------------------------------------------------------
    def _query_setup(self, query_json: dict, timeout_s: Optional[float],
                     tl) -> "_QueryCtx":
        """Shared front half of a query on ANY edge: budget, decode,
        state snapshot, fault point, deadline-aware admission.  Marks
        the ``parse``/``auth`` timeline boundaries.  Runs on the
        calling thread (event-loop thread or HTTP handler thread) and
        never blocks."""
        # the request's time budget: per-request override, else the
        # configured default, else unbounded (None costs nothing)
        budget = timeout_s if timeout_s is not None \
            else self.config.query_timeout_s
        deadline = Deadline.after(budget) if budget is not None else None
        # pio-hive: route to the tenant FIRST — quota and the
        # per-tenant breaker shed inside resolve(), before any decode
        # or device work spends on a query its tenant cannot serve
        lease = None
        if self.tenants is not None:
            lease = self.tenants.resolve(query_json)
        try:
            decoder = (lease.runtime.query_decoder if lease is not None
                       else self.query_decoder)
            query = decoder(query_json)
            tl.mark("parse")
            if lease is not None:
                rt = lease.runtime
                ctx = _QueryCtx(
                    query=query,
                    deadline=deadline,
                    algorithms=rt.algorithms,
                    models=rt.models,
                    serving=rt.serving,
                    batcher=rt.batcher,
                    freshness=time.monotonic() - rt.model_advanced_mono,
                    foldin_seq=max(
                        rt.foldin_applied_seq.values(), default=0
                    ),
                    lease=lease,
                )
            else:
                with self._lock:
                    ctx = _QueryCtx(
                        query=query,
                        deadline=deadline,
                        algorithms=self.algorithms,
                        models=self.models,
                        serving=self.serving,
                        batcher=self.batcher,
                        # pio-live attribution, captured with the
                        # snapshot: a slow query concurrent with a
                        # fold-in apply is explicable from its flight
                        # record alone
                        freshness=time.monotonic()
                        - self.model_advanced_mono,
                        foldin_seq=max(
                            self.foldin_applied_seq.values(), default=0
                        ),
                    )
            faults.check("device.dispatch")
            if lease is not None:
                faults.check_tenant("tenant.dispatch", lease.key_str)
            tl.mark("auth")
            if deadline is not None:
                # deadline-aware admission (pio-surge): a request that
                # cannot make its SLO is answered a structured 503 NOW
                # instead of queued to die.  The breaker is the
                # cheap-shed mode: after repeated rejects it opens and
                # deadlined requests shed without estimator math until
                # a success.  With a lease, the TENANT's breaker
                # already gated inside resolve() (re-calling allow()
                # here would strand its half-open probe); rejects feed
                # it through lease.complete below.
                if lease is None and not self._admission_breaker.allow():
                    raise AdmissionRejected(
                        "admission breaker open: the edge is shedding "
                        "deadlined requests (overload)"
                    )
                try:
                    if ctx.batcher is not None:
                        ctx.batcher.check_admission(deadline)
                    else:
                        deadline.check("query admission")
                except AdmissionRejected:
                    if lease is None:
                        self._admission_breaker.record_failure()
                    raise
            return ctx
        except BaseException as e:
            if lease is not None:
                lease.complete(_lease_status(e))
            raise

    def _query_finish(self, ctx: "_QueryCtx", predictions, tl, t0: float,
                      query_json: dict) -> Any:
        """Shared back half: serving.serve, JSON encode, stats/
        histogram/trace/flight bookkeeping, feedback injection.  Runs
        on whatever thread completed the device work."""
        if ctx.deadline is not None:
            ctx.deadline.check("query serving")
        result = ctx.serving.serve(ctx.query, predictions)
        out = _result_to_json(result)
        lease = ctx.lease
        if lease is not None and isinstance(out, dict):
            # the assigned variant rides the reply so clients can echo
            # it (with prId) on their conversion events — the
            # attribution loop online eval closes
            out = {**out, "variant": lease.variant}
        tl.mark("serialize")
        self._admission_breaker.record_success()
        dt = time.perf_counter() - t0
        with self._lock:
            self.request_count += 1
            self.last_serving_sec = dt
            instance_id = self.instance_id
        # the request's trace id rides the histograms as a bucket
        # exemplar AND keys the flight record — /metrics names a trace,
        # the flight recorder holds its span tree, one grep joins them.
        # The segment decomposition + pio-live freshness ride BOTH the
        # span attrs and the flight record, so a worst-N entry already
        # says which segment ate the time (write lands only in the
        # histogram family: the record is captured before the socket
        # write).
        tid = current_trace_id()
        self._latency.observe(dt, exemplar=tid)
        self._m_latency.observe(dt, exemplar=tid)
        self._m_engine_queries["ok"].inc()
        attrs = {
            "instance": instance_id,
            "engine": self.engine_name,
            "modelFreshnessSec": round(max(ctx.freshness, 0.0), 3),
            "segmentsMs": tl.snapshot_ms(),
        }
        if ctx.foldin_seq:
            attrs["foldinSeq"] = ctx.foldin_seq
        if lease is not None:
            # pio-hive: per-tenant latency histogram + online-eval
            # impression + trace/flight attribution (a slow query's
            # flight record names its tenant AND variant)
            attrs["tenant"] = lease.key_str
            attrs["variant"] = lease.variant
            lease.observe_latency(dt, exemplar=tid)
            self.tenants.online.impression(
                lease.runtime.spec.app, lease.variant
            )
        # start is back-dated to the request's beginning (pio-lens):
        # tracecat nests spans by interval containment across
        # processes, so serve.query must COVER its measured window,
        # not sit at its end
        get_tracer().record("serve.query", dt, attrs=attrs,
                            start=time.time() - dt)
        get_flight_recorder().offer(
            tid, dt, name="serve.query", attrs=attrs
        )
        if self.config.feedback and self.config.event_server_url:
            out = self._send_feedback(query_json, out, lease=lease)
        if lease is not None:
            lease.complete("ok")
        return out

    def predict_json(self, query_json: dict,
                     timeout_s: Optional[float] = None) -> Any:
        """Blocking query path (threading edge, direct library callers,
        benches).  The event-loop edge uses the same setup/finish
        halves around a continuous ``submit_nowait`` instead."""
        # pulse timeline: adopt the HTTP handler's (its t0 covers body
        # read + JSON decode, and it adds the socket-write segment
        # after the reply) or own a fresh one for direct callers
        # (benches, tests) — either way the batcher finds it via the
        # thread-local scope and credits queue/batch/device waits
        tl = timeline.current_timeline()
        owned = tl is None
        if owned:
            tl = timeline.Timeline("serve")
        t0 = time.perf_counter()
        _m_inflight.inc()
        ctx = None
        try:
            with timeline.timeline_scope(tl), annotate("pio.serve.query"):
                ctx = self._query_setup(query_json, timeout_s, tl)
                with deadline_scope(ctx.deadline):
                    if ctx.deadline is not None:
                        # checked at the device boundary: dispatching a
                        # batched XLA call for a request whose client
                        # gave up wastes the one resource concurrency
                        # shares — the device queue
                        ctx.deadline.check("query device dispatch")
                    if ctx.batcher is not None:
                        # concurrent requests coalesce into one batched
                        # device call (serve() stays per-request on the
                        # caller's thread); the batcher books the
                        # queue_wait/batch_wait/device segments
                        predictions = ctx.batcher.submit(
                            ctx.query, deadline=ctx.deadline
                        )
                    else:
                        predictions = [
                            algo.predict(model, ctx.query)
                            for algo, model in zip(ctx.algorithms,
                                                   ctx.models)
                        ]
                        tl.mark("device")
                    out = self._query_finish(
                        ctx, predictions, tl, t0, query_json
                    )
        except BaseException as e:
            # _query_setup completes its own lease on setup failures;
            # this covers post-setup failures (device, serve, deadline)
            if ctx is not None and ctx.lease is not None:
                ctx.lease.complete(_lease_status(e))
            raise
        finally:
            _m_inflight.dec()
        if owned:
            tl.finish()
        return out

    # -- event-loop edge (pio-surge) ---------------------------------------
    def _build_httpd(self):
        if self.config.edge != "eventloop":
            return super()._build_httpd()
        from .eventloop import EventLoopHTTPServer

        if self._aux_pool is None:
            import concurrent.futures

            # blocking routes only (status/reload/profile/fold-in and
            # unbatched predicts) — the query hot path never lands here
            self._aux_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="serve-aux",
                initializer=scope.register_thread_role,
                initargs=("serve_aux",),
            )
        return EventLoopHTTPServer(
            (self.host, self.port), self._el_handle,
            max_connections=self.config.max_connections,
            name="serving",
        )

    def _aux_submit(self, respond, fn) -> None:
        """Hand a blocking route to the aux pool; if the pool is gone
        (server stopping) answer 503 instead of crashing the loop."""
        try:
            self._aux_pool.submit(fn)
        except RuntimeError:
            try:
                respond(503, {"message": "server is stopping"})
            except RuntimeError:
                pass

    def _aux(self, respond, fn, *args) -> None:
        """Run ``fn(*args) -> (code, payload, ctype, extra_headers)``
        on the aux pool and answer from there."""
        def run():
            try:
                code, payload, ctype, extra = fn(*args)
                respond(code, payload, ctype=ctype, extra_headers=extra)
            except Exception as e:
                logger.exception("aux route failed")
                try:
                    respond(500, {"message": str(e)})
                except RuntimeError:
                    pass  # route answered before raising

        self._aux_submit(respond, run)

    @callback_scope
    def _el_handle(self, req, respond) -> None:
        """Event-loop request router: runs ON the loop thread — every
        branch either answers inline from in-memory state or hands off
        (batcher dispatcher / aux pool) without blocking."""
        u = urllib.parse.urlparse(req.path)
        path = u.path
        if req.method == "POST":
            if path == "/queries.json":
                self._el_query(req, u.query, respond)
            elif path == "/stop":
                respond(200, {"message": "stopping"})
                threading.Thread(target=self.stop, daemon=True).start()
            elif path == "/foldin/apply":
                self._aux(respond, self._blocking_foldin_apply)
            elif path == "/tenants/weights":
                self._aux(respond, self._blocking_set_weights, req.body)
            elif path == "/admin/tenants":
                self._aux(respond, self._blocking_admin_tenants,
                          req.body)
            else:
                respond(404, {"message": "not found"})
            return
        if req.method == "GET":
            accept = req.header("accept", "") or ""
            self._aux(respond, self._blocking_get, path, u.query, accept)
            return
        respond(405, {"message": f"method {req.method} not allowed"})

    def _blocking_get(self, path: str, query: str, accept: str):
        ans = observability_response(path, query)
        if ans is not None:
            code, payload, ctype = ans
            return code, payload, ctype or "application/json", ()
        if path == "/debug/tenants":
            if self.tenants is None:
                return (404, {"message": "tenancy is not enabled "
                              "(deploy --multi)"},
                        "application/json", ())
            return (200, self.tenants.debug_payload(),
                    "application/json", ())
        if path == "/debug/experiments":
            return (*_experiments_response(self.tenants),
                    "application/json", ())
        if path == "/":
            if "text/html" in accept:
                return (200, self.status_html().encode(),
                        "text/html; charset=utf-8", ())
            return 200, self.status_json(), "application/json", ()
        if path == "/reload":
            try:
                iid = self.reload()
                return 200, {"reloaded": iid}, "application/json", ()
            except LookupError as e:
                return 404, {"message": str(e)}, "application/json", ()
            except Exception as e:
                logger.exception("reload failed")
                return (500, {"message": f"reload failed: {e}"},
                        "application/json", ())
        return 404, {"message": "not found"}, "application/json", ()

    def _blocking_foldin_apply(self):
        """POST /foldin/apply: apply any pending fold-in delta links
        NOW (the router's rolling delta push calls this per replica —
        push semantics on top of the poll machinery).  With tenancy
        on, every resident tenant's chain is walked too."""
        n = self._apply_available_deltas()
        if self.tenants is not None:
            n += self.tenants.apply_available_deltas()
        out = {"applied": n}
        out.update(self._foldin_status())
        return 200, out, "application/json", ()

    def _blocking_set_weights(self, raw: bytes):
        """POST /tenants/weights: hot-update an app's A/B variant
        weights — ``{"app": ..., "weights": {"variant": w, ...}}``.
        The router broadcasts this to every replica so the whole fleet
        assigns identically."""
        if self.tenants is None:
            return (404, {"message": "tenancy is not enabled"},
                    "application/json", ())
        try:
            doc = json.loads(raw.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return (400, {"message": f"invalid JSON: {e}"},
                    "application/json", ())
        app = doc.get("app")
        weights = doc.get("weights")
        if not app or not isinstance(weights, dict) or not weights:
            return (400, {"message": "body needs app + weights{}"},
                    "application/json", ())
        try:
            snap = self.tenants.set_weights(str(app), weights)
        except KeyError as e:
            return 404, {"message": str(e)}, "application/json", ()
        except (TypeError, ValueError) as e:
            return 400, {"message": str(e)}, "application/json", ()
        return 200, {"updated": snap}, "application/json", ()

    def _blocking_admin_tenants(self, raw: bytes):
        """POST /admin/tenants: live tenant lifecycle (ROADMAP 5d) —
        ``{"action": "add", "tenant": {...manifest-entry fields...}}``
        registers a tenant without redeploy (model loads lazily on its
        first query, budget rules apply); ``{"action": "remove",
        "app": ..., "variant": ...}`` stops new queries immediately,
        drains in-flight leases, and unloads.  Guarded: 404 without
        tenancy, the anchor tenant is never removable, malformed specs
        answer 400.  The router broadcasts this route fleet-wide."""
        if self.tenants is None:
            return (404, {"message": "tenancy is not enabled"},
                    "application/json", ())
        try:
            doc = json.loads(raw.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return (400, {"message": f"invalid JSON: {e}"},
                    "application/json", ())
        action = doc.get("action")
        if action == "add":
            t = doc.get("tenant")
            if not isinstance(t, dict):
                return (400, {"message": "body needs a tenant{} object"},
                        "application/json", ())
            from ..tenancy import TenantSpec

            try:
                spec = TenantSpec(
                    app=t.get("app", ""),
                    variant=t.get("variant", "default"),
                    engine_json=t.get("engineJson"),
                    engine_name=t.get("engine"),
                    instance_id=t.get("engineInstanceId"),
                    access_key=t.get("accessKey"),
                    weight=float(t.get("weight", 1.0)),
                    pinned=bool(t.get("pinned", False)),
                    quota_qps=t.get("quotaQps"),
                    quota_burst=t.get("quotaBurst"),
                )
            except (TypeError, ValueError) as e:
                return (400, {"message": str(e)},
                        "application/json", ())
            # resolve app id + default access key from metadata (the
            # same enrichment `deploy --multi` does at boot)
            try:
                md = self.ctx.storage.get_metadata()
                app_rec = md.app_get_by_name(spec.app)
                if app_rec is not None:
                    spec.app_id = app_rec.id
                    if spec.access_key is None:
                        keys = md.access_key_get_by_app(app_rec.id)
                        if keys:
                            spec.access_key = keys[0].key
            except Exception:
                logger.exception(
                    "tenant add: metadata enrichment failed; "
                    "accessKey routing is off for %s", spec.key_str,
                )
            try:
                out = self.tenants.add_tenant(spec)
            except ValueError as e:
                return (400, {"message": str(e)},
                        "application/json", ())
            return 200, out, "application/json", ()
        if action == "remove":
            app = doc.get("app")
            if not app:
                return (400, {"message": "remove needs an app"},
                        "application/json", ())
            try:
                out = self.tenants.remove_tenant(
                    (str(app), str(doc.get("variant", "default"))),
                    drain_timeout_s=float(
                        doc.get("drainTimeoutSec", 10.0)
                    ),
                )
            except KeyError as e:  # UnknownTenant ⊂ KeyError
                return 404, {"message": str(e)}, "application/json", ()
            except ValueError as e:
                return 400, {"message": str(e)}, "application/json", ()
            return 200, out, "application/json", ()
        return (400, {"message": "action must be 'add' or 'remove'"},
                "application/json", ())

    @callback_scope
    def _el_query(self, req, query_str: str, respond) -> None:
        """The continuous hot path: parse + admission on the loop
        thread, device work on the batcher dispatcher, completion
        (serve/encode/bookkeeping) on the dispatcher's callback, socket
        write back on the loop.  One request never parks a thread."""
        tid = (req.header(TRACE_HEADER) or "").strip() or new_trace_id()
        hdrs = [(TRACE_HEADER, tid)]
        tl = timeline.Timeline("serve")
        try:
            query_json = json.loads(req.body.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            self._m_queries["bad_request"].inc()
            respond(400, {"message": f"invalid JSON: {e}"},
                    extra_headers=hdrs)
            return
        timeout_s = None
        tv = urllib.parse.parse_qs(query_str).get("timeout")
        if tv:
            try:
                timeout_s = float(tv[0])
            except ValueError:
                self._m_queries["bad_request"].inc()
                respond(400, {"message": f"bad timeout: {tv[0]!r}"},
                        extra_headers=hdrs)
                return
        _m_inflight.inc()
        try:
            with trace_scope(tid), timeline.timeline_scope(tl):
                ctx = self._query_setup(query_json, timeout_s, tl)
        except Exception as e:
            _m_inflight.dec()
            self._el_reply_error(e, respond, hdrs)
            return

        if ctx.batcher is None:
            # no batched path for this engine: the per-query predict is
            # blocking device work — aux pool, not the loop
            def run_direct():
                try:
                    with trace_scope(tid), timeline.timeline_scope(tl), \
                            deadline_scope(ctx.deadline), \
                            annotate("pio.serve.query"):
                        if ctx.deadline is not None:
                            ctx.deadline.check("query device dispatch")
                        predictions = [
                            algo.predict(model, ctx.query)
                            for algo, model in zip(ctx.algorithms,
                                                   ctx.models)
                        ]
                        tl.mark("device")
                        out = self._query_finish(
                            ctx, predictions, tl, tl.t0, query_json
                        )
                except Exception as e:
                    _m_inflight.dec()
                    self._el_reply_error(e, respond, hdrs,
                                         lease=ctx.lease)
                    return
                _m_inflight.dec()
                self._m_queries["ok"].inc()
                respond(200, out, extra_headers=hdrs, tl=tl)

            self._aux_submit(respond, run_direct)
            return

        def done(entry):
            # batcher dispatcher thread: the entry's timeline is booked
            # (queue_wait/batch_wait/device) before this fires
            err = entry.error
            out = None
            if err is None:
                try:
                    with trace_scope(tid), deadline_scope(ctx.deadline):
                        out = self._query_finish(
                            ctx, entry.value, tl, tl.t0, query_json
                        )
                except Exception as e:
                    err = e
            _m_inflight.dec()
            if err is not None:
                self._el_reply_error(err, respond, hdrs, lease=ctx.lease)
                return
            self._m_queries["ok"].inc()
            respond(200, out, extra_headers=hdrs, tl=tl)

        try:
            ctx.batcher.submit_nowait(
                ctx.query, done, deadline=ctx.deadline, timeline=tl
            )
        except RuntimeError:
            # the snapshot raced a reload that closed this batcher:
            # retry once on the current one (single-tenant path only —
            # a tenant's batcher is replaced only by its own reload)
            with self._lock:
                batcher = self.batcher
            if (ctx.lease is None and batcher is not None
                    and batcher is not ctx.batcher):
                ctx.batcher = batcher
                batcher.submit_nowait(
                    ctx.query, done, deadline=ctx.deadline, timeline=tl
                )
            else:
                _m_inflight.dec()
                self._el_reply_error(
                    RuntimeError("batcher unavailable during reload"),
                    respond, hdrs, lease=ctx.lease,
                )

    def _el_reply_error(self, e: BaseException, respond, hdrs,
                        lease=None) -> None:
        """Map a query-path exception to the same structured replies
        the threading edge produces (and the same counters).  A lease
        passed here books the tenant outcome (idempotent — setup
        failures were already completed inside ``_query_setup``)."""
        if lease is not None:
            lease.complete(_lease_status(e))
        self._book_engine_query(_lease_status(e))
        try:
            if isinstance(e, QuotaExceeded):
                # per-tenant token bucket: the client is over ITS
                # rate, not the server over capacity — 429, not 503
                self._m_queries["rejected"].inc()
                respond(429, {"message": str(e),
                              "error": "QuotaExceeded"},
                        extra_headers=hdrs + [("Retry-After", "1")])
            elif isinstance(e, TenantUnavailable):
                self._m_queries["rejected"].inc()
                respond(503, {"message": str(e),
                              "error": "TenantUnavailable"},
                        extra_headers=hdrs + [("Retry-After", "1")])
            elif isinstance(e, AdmissionRejected):
                self._m_queries["rejected"].inc()
                respond(503, {"message": str(e),
                              "error": "AdmissionRejected"},
                        extra_headers=hdrs + [("Retry-After", "1")])
            elif isinstance(e, DeadlineExceeded):
                self._m_queries["timeout"].inc()
                respond(503, {"message": str(e),
                              "error": "DeadlineExceeded"},
                        extra_headers=hdrs + [("Retry-After", "1")])
            elif isinstance(e, (KeyError, ValueError, TypeError)):
                self._m_queries["bad_request"].inc()
                respond(400, {"message": f"bad query: {e}"},
                        extra_headers=hdrs)
                self.remote_log(f"Query is invalid: {e}")
            else:
                self._m_queries["error"].inc()
                logger.error("query failed: %s", e)
                respond(500, {"message": str(e)}, extra_headers=hdrs)
                self.remote_log(f"Query failed: {e}")
        except RuntimeError:
            pass  # request already answered

    def _book_engine_query(self, status: str) -> None:
        """Book one engine-labeled outcome (unknown statuses fold into
        'error' so the label space stays bounded)."""
        child = self._m_engine_queries.get(status)
        (child if child is not None
         else self._m_engine_queries["error"]).inc()

    def _send_feedback(self, query_json: dict, result_json: Any,
                       lease=None) -> Any:
        """Enqueue a pio_pr feedback event with prId injection, off the
        hot path (reference `CreateServer.scala:480-550` does this async
        too).  The bounded delivery queue retries with backoff behind a
        circuit breaker, so a down event server neither stalls serving
        nor loses events below queue capacity — they deliver when it
        returns."""
        pr_id = (
            result_json.get("prId") if isinstance(result_json, dict) else None
        ) or uuid.uuid4().hex
        props = {"query": query_json, "prediction": result_json}
        access_key = self.config.access_key
        if lease is not None:
            # pio-hive: the A/B attribution tag — every feedback event
            # flowing back through the event store names its (app,
            # variant), which is what makes interleaved serving an
            # ONLINE evaluation (online_eval.py scans these back out)
            props["variant"] = lease.variant
            props["app"] = lease.runtime.spec.app
            if lease.runtime.spec.access_key:
                access_key = lease.runtime.spec.access_key
        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": pr_id,
            "properties": props,
        }
        url = (
            f"{self.config.event_server_url}/events.json"
            f"?accessKey={access_key or ''}"
        )
        from ..obs import current_trace_id

        tid = current_trace_id()
        self._feedback_queue.submit(
            url, event, headers={TRACE_HEADER: tid} if tid else None
        )
        if isinstance(result_json, dict):
            result_json = {**result_json, "prId": pr_id}
        return result_json

    def remote_log(self, message: str) -> None:
        """Ship a serving error to the configured remote log endpoint
        (reference `CreateServer.scala:413-424` ``remoteLog``): POST
        ``log_prefix + json({engineInstance, message})`` off the hot
        path via the delivery queue; delivery failures are retried then
        counted, never raised."""
        if not self.config.log_url:
            return
        with self._lock:
            instance_id = self.instance_id
        payload = self.config.log_prefix + json.dumps({
            "engineInstance": {
                "id": instance_id,
                "engineId": self.engine_id,
                "engineVersion": self.engine_version,
                "engineVariant": self.engine_variant,
            },
            "message": message,
        })
        self._log_queue.submit(self.config.log_url, payload.encode())

    def latency_stats(self) -> dict:
        """Histogram-backed latency view for /status: the same buckets
        /metrics exposes, so an operator's curl and their Grafana panel
        cannot disagree.  ``avg`` keeps the old ``avgServingSec``
        contract (now sum/count, no incremental-mean drift)."""
        snap = self._latency.snapshot()
        if snap["count"] == 0:
            return {"count": 0, "avg": 0.0, "p50": 0.0, "p95": 0.0,
                    "p99": 0.0}
        return {
            "count": snap["count"],
            "avg": snap["sum"] / snap["count"],
            "p50": self._latency.percentile(50, snap),
            "p95": self._latency.percentile(95, snap),
            "p99": self._latency.percentile(99, snap),
        }

    def status_json(self) -> dict:
        # snapshot the hot-swapped / request-updated state under the
        # lock; the reload thread and in-flight queries mutate it
        with self._lock:
            instance_id = self.instance_id
            request_count = self.request_count
            last_serving_sec = self.last_serving_sec
            batcher = self.batcher
            last_reload_error = self.last_reload_error
        lat = self.latency_stats()
        out = {
            "status": "alive",
            "engineInstanceId": instance_id,
            "engineId": self.engine_id,
            "engineVersion": self.engine_version,
            "engineVariant": self.engine_variant,
            "requestCount": request_count,
            "avgServingSec": lat["avg"],
            "lastServingSec": last_serving_sec,
            "p50ServingSec": lat["p50"],
            "p95ServingSec": lat["p95"],
            "p99ServingSec": lat["p99"],
            "startTime": self.start_time,
        }
        if batcher is not None:
            # locked snapshot — the counters are mutated under the
            # batcher's condition by whichever thread leads a batch
            out["microbatch"] = batcher.stats()
        # pio-live: model freshness + watermark lag (absent when off)
        out.update(self._foldin_status())
        # failure observability: queue depths/drops, breaker states, and
        # the last reload error an operator should know about
        out["resilience"] = {
            "lastReloadError": last_reload_error,
            "queryTimeoutSec": self.config.query_timeout_s,
            "feedback": self._feedback_queue.stats(),
            "remoteLog": self._log_queue.stats(),
        }
        # pio-hive: registry residency/budget counters (full per-tenant
        # detail lives on /debug/tenants)
        if self.tenants is not None:
            out["tenancy"] = self.tenants.summary()
        # pio-xray: the worst-N flight records (ids + durations; full
        # span trees live on /debug/xray) and the histogram's bucket
        # exemplars, so /status alone links a slow bucket to a trace id
        out["xray"] = {
            "flight": get_flight_recorder().summary(),
            "latencyExemplars": [
                {"le": le, "traceId": ex, "value": v}
                for le, ex, v, _ts in self._latency.exemplar_items()
            ],
        }
        return out

    def status_html(self) -> str:
        """Browser view of the deployed engine (reference's Twirl status
        page, `core/src/main/twirl/io/prediction/workflow/index.scala.html`):
        engine + server info and per-component params.  Same data as
        :meth:`status_json`; content-negotiated on ``/``."""
        import html as _html

        from ..controller.params import params_to_json

        def esc(v) -> str:
            return _html.escape(str(v))

        def row(k, v) -> str:
            return f"<tr><th>{esc(k)}</th><td>{esc(v)}</td></tr>"

        def table(rows) -> str:
            return "<table border='1' cellpadding='4'>" + "".join(rows) + "</table>"

        with self._lock:
            instance_id = self.instance_id
            request_count = self.request_count
            last_serving_sec = self.last_serving_sec
            ep = self.engine_params
        lat = self.latency_stats()
        rec = self.ctx.storage.get_metadata().engine_instance_get(
            instance_id
        )
        engine_rows = [
            row("Instance ID", instance_id),
            row("Engine ID", self.engine_id),
            row("Engine Version", self.engine_version),
            row("Variant", self.engine_variant),
        ]
        if rec is not None:
            engine_rows += [
                row("Training Start Time", rec.start_time),
                row("Training End Time", rec.end_time),
            ]
        started = time.strftime(
            "%Y-%m-%d %H:%M:%S UTC", time.gmtime(self.start_time)
        )
        server_rows = [
            row("Start Time", started),
            row("Request Count", request_count),
            row("Average Serving Time", f"{lat['avg']:.4f} s"),
            row("Last Serving Time", f"{last_serving_sec:.4f} s"),
            row("Serving Time p50 / p95 / p99",
                f"{lat['p50']:.4f} / {lat['p95']:.4f} / "
                f"{lat['p99']:.4f} s"),
        ]
        live = self._foldin_status()
        if live:
            server_rows.append(row(
                "Model Freshness (pio-live)",
                f"{live['modelFreshnessSec']:.1f} s since last advance; "
                f"watermark lag {live['foldinWatermarkLag']} rows; "
                f"{live['foldinDeltasApplied']} deltas applied",
            ))
        worst = get_flight_recorder().summary()["worst"]
        if worst:
            server_rows.append(row(
                "Slowest Requests (flight recorder)",
                "; ".join(
                    f"{w['traceId']} {w['durationSec'] * 1e3:.1f} ms"
                    for w in worst[:5]
                ) + " — span trees at /debug/xray",
            ))
        comp_rows = [
            row(f"Data Source [{ep.data_source[0] or 'default'}]",
                json.dumps(params_to_json(ep.data_source[1]))),
            row(f"Preparator [{ep.preparator[0] or 'default'}]",
                json.dumps(params_to_json(ep.preparator[1]))),
        ]
        for name, p in ep.algorithms:
            comp_rows.append(
                row(f"Algorithm [{name or 'default'}]",
                    json.dumps(params_to_json(p)))
            )
        comp_rows.append(
            row(f"Serving [{ep.serving[0] or 'default'}]",
                json.dumps(params_to_json(ep.serving[1])))
        )
        title = (
            f"Engine Server at {self.config.host}:{self.config.port}"
        )
        return (
            "<!DOCTYPE html><html><head>"
            f"<title>{esc(title)}</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "td{font-family:monospace}</style></head><body>"
            f"<h1>{esc(title)}</h1>"
            "<h2>Engine Information</h2>" + table(engine_rows) +
            "<h2>Server Information</h2>" + table(server_rows) +
            "<h2>Components</h2>" + table(comp_rows) +
            "<p>POST queries to <code>/queries.json</code>.</p>"
            "</body></html>"
        )

    def stop(self) -> None:
        super().stop()
        # release the delta-poll, batcher-dispatcher, aux and delivery
        # drain threads (pending entries are abandoned — the process is
        # going away)
        self._foldin_stop.set()
        self._eval_stop.set()
        if self.tenants is not None:
            self.tenants.close()
        with self._lock:
            batcher = getattr(self, "batcher", None)
        if batcher is not None:
            batcher.close()
        # pio-confluence: a view's close only retires its tenant; the
        # shared core (and its dispatcher thread) is the server's to
        # stop
        with self._shared_lock:
            core, self._shared_core = self._shared_core, None
        if core is not None:
            core.close()
        if self._aux_pool is not None:
            self._aux_pool.shutdown(wait=False)
            self._aux_pool = None
        self._feedback_queue.close()
        self._log_queue.close()

    # -- http --------------------------------------------------------------
    @property
    def host(self) -> str:
        return self.config.host

    @property
    def port(self) -> int:
        return self.config.port

    @port.setter
    def port(self, v: int) -> None:
        self.config.port = v

    @property
    def max_connections(self) -> int:
        return self.config.max_connections

    def _make_handler(server: "EngineServer"):
        # labeled counter children resolved ONCE: .labels() is a dict
        # build + lock per call (~1.5 us), too hot for per-request use
        m_ok = QUERIES_TOTAL.labels(status="ok")
        m_bad = QUERIES_TOTAL.labels(status="bad_request")
        m_timeout = QUERIES_TOTAL.labels(status="timeout")
        m_err = QUERIES_TOTAL.labels(status="error")
        m_rejected = QUERIES_TOTAL.labels(status="rejected")

        class Handler(JsonRequestHandler):
            server_logger = logger

            def do_GET(self):
                if self._serve_metrics():
                    return
                if self.path == "/" or self.path.startswith("/?"):
                    # browsers get the HTML status page, everyone else the
                    # JSON document (reference served Twirl HTML here)
                    if "text/html" in self.headers.get("Accept", ""):
                        self._reply(
                            200, server.status_html().encode(),
                            ctype="text/html; charset=utf-8",
                        )
                    else:
                        self._reply(200, server.status_json())
                elif self.path.startswith("/reload"):
                    try:
                        iid = server.reload()
                        self._reply(200, {"reloaded": iid})
                    except LookupError as e:
                        self._reply(404, {"message": str(e)})
                    except Exception as e:
                        logger.exception("reload failed")
                        self._reply(500, {"message": f"reload failed: {e}"})
                elif self.path.startswith("/debug/tenants"):
                    if server.tenants is None:
                        self._reply(404, {"message": "tenancy is not "
                                          "enabled (deploy --multi)"})
                    else:
                        self._reply(200, server.tenants.debug_payload())
                elif self.path.startswith("/debug/experiments"):
                    self._reply(*_experiments_response(server.tenants))
                else:
                    self._reply(404, {"message": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b"{}"
                if self.path.startswith("/queries.json"):
                    # trace propagation: honor the client's X-PIO-Trace
                    # or mint one; either way the id is bound to this
                    # thread (spans inherit it, feedback delivery
                    # forwards it) and echoed on the response.
                    # extra_headers is (re)assigned per request — a
                    # keep-alive connection reuses this handler.
                    tid = self._trace_id() or new_trace_id()
                    self.extra_headers = [(TRACE_HEADER, tid)]
                    # the handler owns the pulse timeline: its t0
                    # precedes JSON decode, and only the handler can
                    # time the socket write of the reply
                    tl = timeline.Timeline("serve")
                    with trace_scope(tid), timeline.timeline_scope(tl):
                        self._post_query(raw, tl)
                elif self.path.startswith("/foldin/apply"):
                    try:
                        code, payload, _, _ = server._blocking_foldin_apply()
                        self._reply(code, payload)
                    except Exception as e:
                        logger.exception("foldin apply failed")
                        self._reply(500, {"message": str(e)})
                elif self.path.startswith("/tenants/weights"):
                    try:
                        code, payload, _, _ = (
                            server._blocking_set_weights(raw)
                        )
                        self._reply(code, payload)
                    except Exception as e:
                        logger.exception("weights update failed")
                        self._reply(500, {"message": str(e)})
                elif self.path.startswith("/admin/tenants"):
                    try:
                        code, payload, _, _ = (
                            server._blocking_admin_tenants(raw)
                        )
                        self._reply(code, payload)
                    except Exception as e:
                        logger.exception("tenant admin failed")
                        self._reply(500, {"message": str(e)})
                elif self.path.startswith("/stop"):
                    self._reply(200, {"message": "stopping"})
                    threading.Thread(target=server.stop, daemon=True).start()
                else:
                    self._reply(404, {"message": "not found"})

            def _post_query(self, raw: bytes, tl) -> None:
                try:
                    query_json = json.loads(raw.decode() or "{}")
                except json.JSONDecodeError as e:
                    m_bad.inc()
                    self._reply(400, {"message": f"invalid JSON: {e}"})
                    return
                # optional per-request budget: /queries.json?timeout=0.5
                timeout_s = None
                tv = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                ).get("timeout")
                if tv:
                    try:
                        timeout_s = float(tv[0])
                    except ValueError:
                        m_bad.inc()
                        self._reply(
                            400, {"message": f"bad timeout: {tv[0]!r}"}
                        )
                        return
                try:
                    self._reply(200, server.predict_json(
                        query_json, timeout_s=timeout_s))
                    # close the timeline on the success path only:
                    # error replies have no meaningful decomposition
                    # and would pollute the per-segment histograms
                    tl.mark("write")
                    tl.finish()
                    m_ok.inc()
                except QuotaExceeded as e:
                    # pio-hive: over the tenant's token bucket — the
                    # client's rate problem, a structured 429
                    m_rejected.inc()
                    server._book_engine_query("quota")
                    self.extra_headers.append(("Retry-After", "1"))
                    self._reply(429, {
                        "message": str(e),
                        "error": "QuotaExceeded",
                    })
                except TenantUnavailable as e:
                    m_rejected.inc()
                    server._book_engine_query("shed")
                    self.extra_headers.append(("Retry-After", "1"))
                    self._reply(503, {
                        "message": str(e),
                        "error": "TenantUnavailable",
                    })
                except AdmissionRejected as e:
                    # deadline-aware admission shed the request before
                    # it queued (pio-surge): same structured 503, its
                    # own counter
                    m_rejected.inc()
                    server._book_engine_query("rejected")
                    self.extra_headers.append(("Retry-After", "1"))
                    self._reply(503, {
                        "message": str(e),
                        "error": "AdmissionRejected",
                    })
                except DeadlineExceeded as e:
                    # structured overload answer, not a hang: the
                    # client can back off and retry
                    m_timeout.inc()
                    server._book_engine_query("timeout")
                    self.extra_headers.append(("Retry-After", "1"))
                    self._reply(503, {
                        "message": str(e),
                        "error": "DeadlineExceeded",
                    })
                except (KeyError, ValueError, TypeError) as e:
                    m_bad.inc()
                    server._book_engine_query("bad_request")
                    self._reply(400, {"message": f"bad query: {e}"})
                    server.remote_log(
                        f"Query {raw.decode(errors='replace')} "
                        f"is invalid: {e}"
                    )
                except Exception as e:
                    m_err.inc()
                    server._book_engine_query("error")
                    logger.exception("query failed")
                    self._reply(500, {"message": str(e)})
                    server.remote_log(
                        f"Query {raw.decode(errors='replace')} "
                        f"failed: {e}"
                    )

        return Handler

