"""HTTP servers: engine deployment (serving), event ingestion, admin,
dashboard (reference L3/L8/L9 surfaces)."""

from .serving import EngineServer, ServerConfig

__all__ = ["EngineServer", "ServerConfig"]
