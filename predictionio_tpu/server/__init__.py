"""HTTP servers: engine deployment (serving), event ingestion, admin,
dashboard (reference L3/L8/L9 surfaces), plus the pio-surge
event-loop edge and the replica-fleet router."""

from .admin import AdminServer
from .dashboard import DashboardServer
from .event_server import EventServer, EventServerConfig
from .router import Replica, RouterConfig, RouterServer
from .serving import EngineServer, ServerConfig
from .stats import StatsCollector

__all__ = [
    "AdminServer",
    "DashboardServer",
    "EventServer",
    "EventServerConfig",
    "EngineServer",
    "Replica",
    "RouterConfig",
    "RouterServer",
    "ServerConfig",
    "StatsCollector",
]
