"""Continuous micro-batching for the serving hot path.

The reference detaches one JVM actor per request
(`workflow/CreateServer.scala:437,464`) and each predict is cheap CPU
work, so concurrency alone scales it.  Here every predict is a device
call, and a TPU has ONE execution queue: N concurrent requests that
each dispatch their own top-k matmul serialize on the queue, so
per-request latency grows ~linearly with concurrency while aggregate
QPS stays flat (measured: 8 threads take p50 from ~1 ms to ~7.5 ms at
unchanged QPS, bench_serving.py --threads).

The TPU-shaped fix is to make concurrency *wider, not deeper*: coalesce
the queries that arrive while a device call is in flight into ONE
batched call (`Algorithm.batch_predict` — a [B, R] x [R, M] matmul
costs barely more than the [R] x [R, M] one).  This is the
leader/follower "continuous batching" pattern:

* a request appends its query to the pending list; if no batch is
  executing, it becomes the LEADER: it takes everything pending (up to
  ``max_batch``) and runs the batch function *on its own thread*;
* requests arriving meanwhile park as FOLLOWERS; the leader's
  completion wakes them — their results are already set, or one of
  them becomes the next leader with the batch that accumulated;
* under no concurrency the pending list always has exactly one entry
  and the batcher degenerates to a direct call: no dispatcher thread,
  no timer, zero added latency at QPS where batching can't help.

Batch size therefore adapts to the arrival rate with no tuning knob
doing latency/throughput trades behind the operator's back
(``max_wait_s`` exists for completeness but defaults to 0).

Determinism note: a batched matmul compiles per batch size, so the same
query served inside different batch compositions can differ at float
ulp scale (different reduction order) — rankings are stable, scores may
wobble ~1e-7.  Deployments that need bitwise per-request determinism
set ``ServerConfig(microbatch="off")``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

from ..obs.timeline import (
    MICROBATCH_BATCH_SIZE,
    MICROBATCH_QUEUE_DEPTH,
    MICROBATCH_ROLE_TOTAL,
    MICROBATCH_WAIT_SECONDS,
    annotate,
    current_timeline,
)

__all__ = ["MicroBatcher", "dispatchable_sizes"]

# pulse saturation metrics, children cached at import (labels() is too
# hot for the per-submit path); process-wide like pio_query_latency —
# one serving process hosts one live batcher
_m_queue_depth = MICROBATCH_QUEUE_DEPTH.child()
_m_batch_size = MICROBATCH_BATCH_SIZE.child()
_m_batch_wait = MICROBATCH_WAIT_SECONDS.child()
_m_leader = MICROBATCH_ROLE_TOTAL.labels(role="leader")
_m_follower = MICROBATCH_ROLE_TOTAL.labels(role="follower")

# distinguishes "no result produced" from a legitimate None result —
# batch_fns whose valid outputs include None must not have them
# clobbered by the leader-abort guard
_UNSET = object()


def _pad_size(n: int) -> int:
    """The batch size ``n`` items actually dispatch as under pow2
    padding — THE definition; the warmup ladder derives from it."""
    return 1 << (n - 1).bit_length() if n > 1 else 1


def dispatchable_sizes(max_batch: int) -> list[int]:
    """Every batch size a padding batcher with this ``max_batch`` can
    dispatch: 1, 2, 4, ..., _pad_size(max_batch).  Template warmups
    build their compile ladders from THIS (templates/_common.pow2_ladder
    delegates here) so a change to the padding scheme cannot silently
    desynchronize warmup from dispatch.

    ``max_batch <= 0`` means "no batcher at all" (serving passes 0 when
    micro-batching is off or auto-gated off): the ladder is EMPTY —
    every request then runs the per-query predict path, and compiling
    batched executables would be pure wasted XLA work at deploy/reload."""
    if max_batch <= 0:
        return []
    top = _pad_size(max_batch)
    b, sizes = 1, []
    while b <= top:
        sizes.append(b)
        b <<= 1
    return sizes


class _Entry:
    # t_enq/t_claim/t_run0/t_run1 are the pulse timeline stamps: set by
    # whichever thread performs the transition (enqueue by the caller,
    # claim by the leader, run bracketing by the executing thread) and
    # read by the caller AFTER ``done`` — the condition variable's
    # release/acquire orders the writes before the read
    __slots__ = ("item", "done", "value", "error",
                 "t_enq", "t_claim", "t_run0", "t_run1")

    def __init__(self, item):
        self.item = item
        self.done = False
        self.value = _UNSET
        self.error: Exception | None = None
        self.t_enq = time.perf_counter()
        self.t_claim = None
        self.t_run0 = None
        self.t_run1 = None


class MicroBatcher:
    """Coalesce concurrent ``submit(x)`` calls into ``batch_fn([x...])``.

    ``batch_fn`` receives a list of items and must return a list of
    results of the same length and order.  An exception from
    ``batch_fn`` fails every request in that batch (callers see the
    same exception a direct call would have raised).
    """

    def __init__(
        self,
        batch_fn: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_wait_s: float = 0.0,
        pad_batches: bool = False,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        # pad each batch to the next power of two by repeating the last
        # item (results sliced off).  An XLA batch_fn compiles ONE
        # executable per distinct batch size; continuous batching
        # naturally produces every size 1..max_batch, which would pay a
        # compile mid-traffic for each new size — measured as a p99
        # spike on first exposure to load.  Padding bounds the
        # executable count to log2(max_batch)+1.  Only valid when
        # batch_fn is a pure per-item map (duplicated trailing items
        # must be harmless), which predicts are.
        self.pad_batches = pad_batches
        self._cond = threading.Condition()
        self._pending: list[_Entry] = []
        self._running = False
        # observability: how the batcher is actually coalescing.
        # Mutated only under _cond; read through stats() (bare reads
        # tore under concurrency — serving status JSON and the benches
        # all go through the locked snapshot now)
        self.batches = 0
        self.requests = 0
        self.max_seen = 0
        self.leaders = 0
        self.followers = 0

    def reset_stats(self) -> None:
        with self._cond:
            self.batches = self.requests = self.max_seen = 0
            self.leaders = self.followers = 0

    def stats(self) -> dict:
        """Locked snapshot of the coalescing counters plus the live
        queue depth — the ONE way to read them (status JSON, benches,
        /pulse.html)."""
        with self._cond:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "maxBatchSeen": self.max_seen,
                "leaders": self.leaders,
                "followers": self.followers,
                "queueDepth": len(self._pending),
            }

    def submit(self, item: Any) -> Any:
        entry = _Entry(item)
        led_own = False
        with self._cond:
            self._pending.append(entry)
            _m_queue_depth.set(float(len(self._pending)))
            # wake a leader sitting in its accumulation window (no-op
            # for followers: they re-check state and wait again)
            self._cond.notify_all()
            while True:
                if entry.done:
                    break
                if not self._running:
                    # become the leader for everything pending now
                    self._running = True
                    batch = self._pending[: self.max_batch]
                    del self._pending[: len(batch)]
                    now = time.perf_counter()
                    for e in batch:
                        e.t_claim = now
                    _m_queue_depth.set(float(len(self._pending)))
                    # role bookkeeping: with > max_batch entries ahead,
                    # the claimed batch may not include our own entry —
                    # then we led for OTHERS and our request is still a
                    # follower of some later batch
                    if any(e is entry for e in batch):
                        led_own = True
                    self._lead(batch)
                    continue  # re-check: our entry is done (we led it)
                self._cond.wait()
            if led_own:
                self.leaders += 1
            else:
                self.followers += 1
        (_m_leader if led_own else _m_follower).inc()
        # credit the caller's pulse timeline with what this entry
        # actually experienced (error requests decompose too)
        self._book_timeline(entry)
        if entry.error is not None:
            raise entry.error
        return entry.value if entry.value is not _UNSET else None

    @staticmethod
    def _book_timeline(entry: _Entry) -> None:
        """Book queue_wait/batch_wait/device from the entry stamps onto
        the thread's current timeline.  Residual time inside the submit
        region (condition wake latency, a solo retry after a failed
        batch) is attributed to ``device`` by add_block, so the
        timeline's segment sum still equals wall time."""
        tl = current_timeline()
        if tl is None:
            return
        parts = []
        if entry.t_claim is not None:
            parts.append(("queue_wait", entry.t_claim - entry.t_enq))
            if entry.t_run0 is not None:
                parts.append(("batch_wait", entry.t_run0 - entry.t_claim))
                if entry.t_run1 is not None:
                    parts.append(("device", entry.t_run1 - entry.t_run0))
        tl.add_block(parts, residual_to="device")

    def _lead(self, batch: list[_Entry]) -> None:
        """Run one batch on the calling thread.  Called with the lock
        HELD; releases it around the device call and re-acquires.

        The ENTIRE leader turn — accumulation window included — sits
        inside one try/finally: a BaseException landing anywhere in it
        (``Condition.wait`` re-acquires the lock before raising, so the
        lock state is consistent) must still mark every claimed entry
        done and clear ``_running``, or the followers block forever and
        every future ``submit`` hangs behind a leaderless batcher."""
        completed = False
        try:
            if self.max_wait_s > 0 and len(batch) < self.max_batch:
                # optional accumulation window (off by default): give
                # near-simultaneous arrivals a chance to join this batch.
                # Arrivals notify; absorb after EVERY wake (timeout
                # included) so nothing queued during the window is left
                # behind for the next leader.
                deadline = time.monotonic() + self.max_wait_s
                while len(batch) < self.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                    take = self.max_batch - len(batch)
                    absorbed = self._pending[:take]
                    del self._pending[:take]
                    if absorbed:
                        now = time.perf_counter()
                        for e in absorbed:
                            e.t_claim = now
                        batch += absorbed
                        _m_queue_depth.set(float(len(self._pending)))
            self._cond.release()
            try:
                self._run_batch(batch)
            finally:
                self._cond.acquire()
            completed = True
        finally:
            for e in batch:
                if not completed and e.value is _UNSET and e.error is None:
                    # a BaseException (KeyboardInterrupt/SystemExit) tore
                    # through the leader: _run_batch's except clause only
                    # handles Exception, so coalesced followers would
                    # otherwise wake with value=None and serve garbage.
                    # The interrupt propagates to the leader's caller;
                    # followers re-raise this instead.
                    e.error = RuntimeError(
                        "batch leader aborted before producing results"
                    )
                e.done = True
            self._running = False
            self.batches += 1
            self.requests += len(batch)
            self.max_seen = max(self.max_seen, len(batch))
            self._cond.notify_all()

    def _run_batch(self, batch: list[_Entry]) -> None:
        """Execute one batch; on failure, isolate the blast radius.

        A batched device call is all-or-nothing, so one malformed query
        would otherwise fail every innocent request coalesced with it
        (per-request dispatch isolated such failures).  On a batch of
        >1 failing, re-run each item ALONE: good requests succeed, the
        bad one gets its own exception — same outcomes as unbatched
        serving, paid only on the rare failure path.
        """
        try:
            items = [e.item for e in batch]
            n = len(items)
            if self.pad_batches and n > 1:
                items = items + [items[-1]] * (_pad_size(n) - n)
            t0 = time.perf_counter()
            for e in batch:
                e.t_run0 = t0
            if batch[0].t_claim is not None:
                # accumulation-window cost: first claim -> dispatch
                _m_batch_wait.observe(max(t0 - batch[0].t_claim, 0.0))
            _m_batch_size.observe(float(n))
            with annotate(f"pio.device.batch{len(items)}"):
                results = self.batch_fn(items)
            t1 = time.perf_counter()
            for e in batch:
                e.t_run1 = t1
            if len(results) != len(items):
                raise RuntimeError(
                    f"batch_fn returned {len(results)} results "
                    f"for {len(items)} items"
                )
            for e, r in zip(batch, results):
                e.value = r
        except Exception as exc:  # noqa: BLE001 — propagate per caller
            if len(batch) == 1:
                batch[0].error = exc
                return
            for e in batch:
                try:
                    (r,) = self.batch_fn([e.item])
                    e.value = r
                except Exception as solo:  # noqa: BLE001
                    e.error = solo
