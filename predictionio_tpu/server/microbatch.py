"""Continuous micro-batching for the serving hot path.

The reference detaches one JVM actor per request
(`workflow/CreateServer.scala:437,464`) and each predict is cheap CPU
work, so concurrency alone scales it.  Here every predict is a device
call, and a TPU has ONE execution queue: N concurrent requests that
each dispatch their own top-k matmul serialize on the queue, so
per-request latency grows ~linearly with concurrency while aggregate
QPS stays flat (measured: 8 threads take p50 from ~1 ms to ~7.5 ms at
unchanged QPS, bench_serving.py --threads).

The TPU-shaped fix is to make concurrency *wider, not deeper*: coalesce
the queries that arrive while a device call is in flight into ONE
batched call (`Algorithm.batch_predict` — a [B, R] x [R, M] matmul
costs barely more than the [R] x [R, M] one).  Two submission paths
share one pending queue and one claim/run core:

* **Blocking** ``submit(x)`` — the original leader/follower pattern:
  a request appends its query; if no batch is executing (and no
  dispatcher owns the queue), it becomes the LEADER and runs the batch
  on its own thread; requests arriving meanwhile park as FOLLOWERS.
  Under no concurrency this degenerates to a direct call — no extra
  thread, no timer, zero added latency.
* **Continuous** ``submit_nowait(x, on_done, ...)`` (pio-surge) — the
  event-loop edge admits requests *into the in-flight queue as they
  arrive* and returns immediately; a lazily-started dispatcher thread
  claims whatever is pending the moment the device frees up and fires
  per-entry completion callbacks.  No thread ever parks per request:
  the edge stays one loop thread + one dispatcher regardless of
  concurrency.

Deadline-aware admission (pio-surge): entries may carry a
``resilience.policy.Deadline``.  A claimed entry already past its
deadline is completed with ``DeadlineExceeded`` WITHOUT ever reaching
the device (the device queue is the one resource concurrency shares —
work for a client that gave up is pure stolen capacity), and
:meth:`MicroBatcher.estimate_wait_s` exposes an EWMA-based estimate of
queue+service time so the serving edge can reject a request that
cannot make its SLO *up front* as a structured 503
(:class:`AdmissionRejected`) rather than queue it to die.

Batch size therefore adapts to the arrival rate with no tuning knob
doing latency/throughput trades behind the operator's back
(``max_wait_s`` exists for completeness but defaults to 0).

Determinism note: a batched matmul compiles per batch size, so the same
query served inside different batch compositions can differ at float
ulp scale (different reduction order) — rankings are stable, scores may
wobble ~1e-7.  Deployments that need bitwise per-request determinism
set ``ServerConfig(microbatch="off")``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional, Sequence

from ..obs.scope import TimedCondition, register_thread_role
from ..obs.timeline import (
    MICROBATCH_ADMISSION_TOTAL,
    MICROBATCH_BATCH_SIZE,
    MICROBATCH_QUEUE_DEPTH,
    MICROBATCH_ROLE_TOTAL,
    MICROBATCH_TENANTS_PER_BATCH,
    MICROBATCH_WAIT_SECONDS,
    annotate,
    current_timeline,
)
from ..resilience.policy import Deadline, DeadlineExceeded

__all__ = [
    "AdmissionRejected",
    "EwmaEstimator",
    "MicroBatcher",
    "SharedBatcher",
    "SharedBatcherView",
    "dispatchable_sizes",
]

logger = logging.getLogger(__name__)

# pulse saturation metrics, children cached at import (labels() is too
# hot for the per-submit path); process-wide like pio_query_latency —
# one serving process hosts one live batcher
_m_queue_depth = MICROBATCH_QUEUE_DEPTH.child()
_m_batch_size = MICROBATCH_BATCH_SIZE.child()
_m_batch_wait = MICROBATCH_WAIT_SECONDS.child()
_m_leader = MICROBATCH_ROLE_TOTAL.labels(role="leader")
_m_follower = MICROBATCH_ROLE_TOTAL.labels(role="follower")
_m_dispatched = MICROBATCH_ROLE_TOTAL.labels(role="dispatched")
_m_adm_rejected = MICROBATCH_ADMISSION_TOTAL.labels(outcome="rejected")
_m_adm_expired = MICROBATCH_ADMISSION_TOTAL.labels(outcome="expired")
_m_tenants_per_batch = MICROBATCH_TENANTS_PER_BATCH.child()

# distinguishes "no result produced" from a legitimate None result —
# batch_fns whose valid outputs include None must not have them
# clobbered by the leader-abort guard
_UNSET = object()


class AdmissionRejected(DeadlineExceeded):
    """The serving edge refused to queue a request that could not make
    its deadline (estimated queue+service time exceeds the remaining
    budget).  A subclass of :class:`DeadlineExceeded` so every existing
    503 path handles it; kept distinct so the edge can count sheds
    separately from in-flight expiries."""


class EwmaEstimator:
    """Exponentially-weighted moving average of observed durations —
    the memory behind deadline-aware admission, shared by the
    micro-batcher (device-batch service time) and the fleet router
    (replica round-trip time; pio-scout satellite).  ``0.0`` until the
    first observation, so a cold estimator never sheds: no evidence
    means admit.  Not synchronized itself — callers serialize
    observations (the batcher under its condition variable, the router
    under its round-robin lock)."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.25):
        self.alpha = alpha
        self.value = 0.0

    def observe(self, dt: float) -> None:
        self.value = (
            dt if self.value <= 0.0
            else self.alpha * dt + (1.0 - self.alpha) * self.value
        )

    def estimate(self) -> float:
        return self.value


def _pad_size(n: int) -> int:
    """The batch size ``n`` items actually dispatch as under pow2
    padding — THE definition; the warmup ladder derives from it."""
    return 1 << (n - 1).bit_length() if n > 1 else 1


def dispatchable_sizes(max_batch: int) -> list[int]:
    """Every batch size a padding batcher with this ``max_batch`` can
    dispatch: 1, 2, 4, ..., _pad_size(max_batch).  Template warmups
    build their compile ladders from THIS (templates/_common.pow2_ladder
    delegates here) so a change to the padding scheme cannot silently
    desynchronize warmup from dispatch.

    ``max_batch <= 0`` means "no batcher at all" (serving passes 0 when
    micro-batching is off or auto-gated off): the ladder is EMPTY —
    every request then runs the per-query predict path, and compiling
    batched executables would be pure wasted XLA work at deploy/reload."""
    if max_batch <= 0:
        return []
    top = _pad_size(max_batch)
    b, sizes = 1, []
    while b <= top:
        sizes.append(b)
        b <<= 1
    return sizes


class _Entry:
    # t_enq/t_claim/t_run0/t_run1 are the pulse timeline stamps: set by
    # whichever thread performs the transition (enqueue by the caller,
    # claim by the leader/dispatcher, run bracketing by the executing
    # thread) and read AFTER ``done`` — the condition variable's
    # release/acquire (blocking path) or the dispatcher's post-batch
    # callback (continuous path) orders the writes before the read
    # tenant/fn are the pio-confluence fields: which tenant the entry
    # belongs to (the WDRR claim key) and which batch_fn executes it
    # (the group key — entries sharing a fn coalesce into ONE device
    # call; None means the owning batcher's own batch_fn).  An entry
    # carries its fn for its whole life, so in-flight queries complete
    # on the model they snapshotted even across a tenant reload.
    __slots__ = ("item", "done", "value", "error", "deadline", "tl",
                 "on_done", "tenant", "fn", "cb_fired",
                 "t_enq", "t_claim", "t_run0", "t_run1")

    def __init__(self, item, deadline: Optional[Deadline] = None,
                 tl=None, on_done: Optional[Callable] = None,
                 tenant=None, fn: Optional[Callable] = None):
        self.item = item
        self.done = False
        self.cb_fired = False
        self.value = _UNSET
        self.error: Exception | None = None
        self.deadline = deadline
        self.tl = tl
        self.on_done = on_done
        self.tenant = tenant
        self.fn = fn
        self.t_enq = time.perf_counter()
        self.t_claim = None
        self.t_run0 = None
        self.t_run1 = None


class MicroBatcher:
    """Coalesce concurrent ``submit(x)`` / ``submit_nowait(x, cb)``
    calls into ``batch_fn([x...])``.

    ``batch_fn`` receives a list of items and must return a list of
    results of the same length and order.  An exception from
    ``batch_fn`` fails every request in that batch (callers see the
    same exception a direct call would have raised).
    """

    def __init__(
        self,
        batch_fn: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_wait_s: float = 0.0,
        pad_batches: bool = False,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        # pad each batch to the next power of two by repeating the last
        # item (results sliced off).  An XLA batch_fn compiles ONE
        # executable per distinct batch size; continuous batching
        # naturally produces every size 1..max_batch, which would pay a
        # compile mid-traffic for each new size — measured as a p99
        # spike on first exposure to load.  Padding bounds the
        # executable count to log2(max_batch)+1.  Only valid when
        # batch_fn is a pure per-item map (duplicated trailing items
        # must be harmless), which predicts are.
        self.pad_batches = pad_batches
        # pio-scope: THE serving hot lock — every submit, claim, and
        # completion passes through this monitor, so its wait
        # histogram is the direct queueing-for-the-batcher evidence
        self._cond = TimedCondition("microbatch")
        self._pending: list[_Entry] = []
        self._running = False
        self._closed = False
        self._dispatcher_alive = False
        # EWMA of recent device-batch service time: the admission
        # estimator's input.  Seeded 0 (= "no evidence, admit"), so a
        # cold batcher never sheds; mutated only under _cond.
        self._ewma = EwmaEstimator()
        # full service time of the last dispatcher/leader turn (all
        # execution groups back-to-back) — what the EWMA observes;
        # written by _run_batch on the leading thread, read by _lead
        # on the same thread under the re-acquired lock
        self._turn_s = 0.0
        # observability: how the batcher is actually coalescing.
        # Mutated only under _cond; read through stats() (bare reads
        # tore under concurrency — serving status JSON and the benches
        # all go through the locked snapshot now)
        self.batches = 0
        self.requests = 0
        self.max_seen = 0
        self.leaders = 0
        self.followers = 0
        self.dispatched = 0
        self.expired = 0

    def reset_stats(self) -> None:
        with self._cond:
            self.batches = self.requests = self.max_seen = 0
            self.leaders = self.followers = 0
            self.dispatched = self.expired = 0

    def stats(self) -> dict:
        """Locked snapshot of the coalescing counters plus the live
        queue depth — the ONE way to read them (status JSON, benches,
        /pulse.html)."""
        with self._cond:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "maxBatchSeen": self.max_seen,
                "leaders": self.leaders,
                "followers": self.followers,
                "dispatched": self.dispatched,
                "expired": self.expired,
                "queueDepth": len(self._pending),
                "dispatcher": self._dispatcher_alive,
                "ewmaBatchSec": self._ewma.value,
            }

    # -- admission (pio-surge) ---------------------------------------------
    def estimate_wait_s(self) -> float:
        """Estimated queue + service time a request admitted NOW would
        experience: (in-flight batch + queued batches ahead + its own
        batch) x the EWMA batch service time.  0.0 until the first
        batch completes — no evidence means admit, never shed."""
        with self._cond:
            ew = self._ewma.value
            if ew <= 0.0:
                return 0.0
            ahead = 1.0 if self._running else 0.0
            ahead += len(self._pending) / float(self.max_batch)
            return (ahead + 1.0) * ew

    def check_admission(self, deadline: Optional[Deadline]) -> None:
        """Raise :class:`AdmissionRejected` when ``deadline`` cannot be
        met even optimistically.  The up-front half of deadline-aware
        admission: a request the estimator already knows will die in
        the queue is answered a structured 503 NOW, costing the client
        one RTT instead of its full timeout."""
        if deadline is None:
            return
        remaining = deadline.remaining()
        if remaining <= 0.0:
            _m_adm_rejected.inc()
            raise AdmissionRejected(
                f"query deadline already exceeded its "
                f"{deadline.budget_s:.3f}s budget at admission"
            )
        est = self.estimate_wait_s()
        if est > remaining:
            _m_adm_rejected.inc()
            raise AdmissionRejected(
                f"estimated queue+service time {est * 1e3:.1f}ms exceeds "
                f"the {remaining * 1e3:.1f}ms remaining of the "
                f"{deadline.budget_s:.3f}s deadline"
            )

    # -- submission paths --------------------------------------------------
    def submit(self, item: Any,
               deadline: Optional[Deadline] = None,
               tenant=None, fn: Optional[Callable] = None) -> Any:
        """Blocking submit: returns the result (or raises) on the
        calling thread.  With no dispatcher running, the classic
        leader/follower flow; with one, the caller parks as a follower
        of the dispatcher's batches.  ``tenant``/``fn`` are the shared-
        batcher routing fields (see :class:`SharedBatcherView`); plain
        batchers leave them None."""
        entry = _Entry(item, deadline=deadline, tenant=tenant, fn=fn)
        led_own = False
        with self._cond:
            self._pending.append(entry)
            _m_queue_depth.set(float(len(self._pending)))
            # wake a leader/dispatcher sitting in its accumulation
            # window (no-op for followers: they re-check and wait)
            self._cond.notify_all()
            while True:
                if entry.done:
                    break
                if not self._running and not self._dispatcher_alive:
                    # become the leader for everything pending now
                    self._running = True
                    batch = self._claim_locked()
                    # role bookkeeping: with > max_batch entries ahead,
                    # the claimed batch may not include our own entry —
                    # then we led for OTHERS and our request is still a
                    # follower of some later batch
                    if any(e is entry for e in batch):
                        led_own = True
                    self._lead(batch)
                    continue  # re-check: our entry is done (we led it)
                self._cond.wait()
            if led_own:
                self.leaders += 1
            else:
                self.followers += 1
        (_m_leader if led_own else _m_follower).inc()
        # credit the caller's pulse timeline with what this entry
        # actually experienced (error requests decompose too)
        self._book_timeline(entry)
        if entry.error is not None:
            raise entry.error
        return entry.value if entry.value is not _UNSET else None

    def submit_nowait(self, item: Any, on_done: Callable[["_Entry"], None],
                      deadline: Optional[Deadline] = None,
                      timeline=None, tenant=None,
                      fn: Optional[Callable] = None) -> None:
        """Continuous (callback) submit: the entry is admitted into the
        pending queue immediately and ``on_done(entry)`` fires — on the
        dispatcher thread, after the entry's timeline is booked — once
        ``entry.value``/``entry.error`` is set.  The lazily-started
        dispatcher claims the next batch the moment the device frees
        up, so arrivals ride the NEXT device call rather than waiting
        out a batch boundary."""
        entry = _Entry(item, deadline=deadline, tl=timeline,
                       on_done=on_done, tenant=tenant, fn=fn)
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if not self._dispatcher_alive:
                self._dispatcher_alive = True
                threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    name="microbatch-dispatch",
                ).start()
            self._pending.append(entry)
            _m_queue_depth.set(float(len(self._pending)))
            self._cond.notify_all()

    def close(self) -> None:
        """Stop accepting ``submit_nowait`` work and let the dispatcher
        drain what is pending, then exit.  Blocking ``submit`` keeps
        working (self-led) — a reload swaps batchers while in-flight
        queries still hold the old one."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- claim/run core (shared by leaders and the dispatcher) -------------
    def _claim_locked(self) -> list[_Entry]:
        batch = self._pending[: self.max_batch]
        del self._pending[: len(batch)]
        now = time.perf_counter()
        for e in batch:
            e.t_claim = now
        _m_queue_depth.set(float(len(self._pending)))
        return batch

    def _dispatch_loop(self) -> None:
        """Standing leader for the continuous path: claims pending
        entries whenever the device is free.  Blocking submitters
        coalesce into its batches as followers."""
        register_thread_role("microbatch_dispatcher")
        with self._cond:
            try:
                while True:
                    while not self._pending and not self._closed:
                        self._cond.wait()
                    if not self._pending and self._closed:
                        break
                    if self._running:
                        # a blocking leader beat us to the claim
                        self._cond.wait()
                        continue
                    self._running = True
                    batch = self._claim_locked()
                    try:
                        self._lead(batch)
                    except Exception:
                        # _lead's finally already completed the batch;
                        # the dispatcher itself must survive (a dead
                        # dispatcher would wedge every future submit)
                        logger.exception("microbatch dispatcher error")
            finally:
                self._dispatcher_alive = False
                self._cond.notify_all()

    def _book_timeline(self, entry: _Entry) -> None:
        """Book queue_wait/batch_wait/device from the entry stamps onto
        the entry's attached timeline (continuous path) or the calling
        thread's current one (blocking path).  Residual time inside the
        covered region (condition wake latency, a solo retry after a
        failed batch) is attributed to ``device`` by add_block, so the
        timeline's segment sum still equals wall time."""
        tl = entry.tl if entry.tl is not None else current_timeline()
        if tl is None:
            return
        parts = []
        if entry.t_claim is not None:
            parts.append(("queue_wait", entry.t_claim - entry.t_enq))
            if entry.t_run0 is not None:
                parts.append(("batch_wait", entry.t_run0 - entry.t_claim))
                if entry.t_run1 is not None:
                    parts.append(("device", entry.t_run1 - entry.t_run0))
        tl.add_block(parts, residual_to="device")

    def _lead(self, batch: list[_Entry]) -> None:
        """Run one claimed batch on the calling thread.  Called with
        the lock HELD; releases it around the device call (and around
        continuous-path callbacks) and re-acquires.

        Claim-time deadline enforcement happens here: entries already
        past their deadline are completed with ``DeadlineExceeded`` and
        never reach the device.

        The ENTIRE leader turn — accumulation window included — sits
        inside one try/finally: a BaseException landing anywhere in it
        (``Condition.wait`` re-acquires the lock before raising, so the
        lock state is consistent) must still mark every claimed entry
        done and clear ``_running``, or the followers block forever and
        every future ``submit`` hangs behind a leaderless batcher."""
        completed = False
        live: list[_Entry] = []
        n_expired = 0
        for e in batch:
            if e.deadline is not None and e.deadline.expired:
                e.error = DeadlineExceeded(
                    f"query expired in the batch queue after "
                    f"{time.perf_counter() - e.t_enq:.3f}s (budget "
                    f"{e.deadline.budget_s:.3f}s); never dispatched"
                )
                n_expired += 1
            else:
                live.append(e)
        if n_expired:
            _m_adm_expired.inc(n_expired)
        try:
            if self.max_wait_s > 0 and live and len(live) < self.max_batch:
                # optional accumulation window (off by default): give
                # near-simultaneous arrivals a chance to join this batch.
                # Arrivals notify; absorb after EVERY wake (timeout
                # included) so nothing queued during the window is left
                # behind for the next leader.
                deadline = time.monotonic() + self.max_wait_s
                while len(live) < self.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                    take = self.max_batch - len(live)
                    absorbed = self._pending[:take]
                    del self._pending[:take]
                    if absorbed:
                        now = time.perf_counter()
                        for e in absorbed:
                            e.t_claim = now
                        live += absorbed
                        batch += absorbed
                        _m_queue_depth.set(float(len(self._pending)))
            if live:
                self._cond.release()
                try:
                    self._run_batch(live)
                finally:
                    self._cond.acquire()
            completed = True
        finally:
            for e in batch:
                if not completed and e.value is _UNSET and e.error is None:
                    # a BaseException (KeyboardInterrupt/SystemExit) tore
                    # through the leader: _run_batch's except clause only
                    # handles Exception, so coalesced followers would
                    # otherwise wake with value=None and serve garbage.
                    # The interrupt propagates to the leader's caller;
                    # followers re-raise this instead.
                    e.error = RuntimeError(
                        "batch leader aborted before producing results"
                    )
                e.done = True
            self._running = False
            if live:
                self.batches += 1
                self.max_seen = max(self.max_seen, len(live))
                # the estimator tracks the FULL turn (every execution
                # group back-to-back), not just the first group's call
                if self._turn_s > 0.0:
                    self._ewma.observe(self._turn_s)
                    self._turn_s = 0.0
            self.requests += len(batch)
            self.expired += n_expired
            # continuous entries get the third role: the dispatcher ran
            # the device call for them, no request thread led anything
            n_disp = sum(1 for e in batch if e.on_done is not None)
            if n_disp:
                self.dispatched += n_disp
                _m_dispatched.inc(n_disp)
            self._cond.notify_all()
            # end-of-turn sweep for continuous-path entries whose
            # callbacks did NOT fire per-group in _run_batch: claim-time
            # deadline expiries (never executed) and anything a
            # BaseException tore past.  Inside the finally so even an
            # aborted leader still answers every event-loop request
            # (their entries carry the leader-abort error by now).
            cbs = [e for e in batch
                   if e.on_done is not None and not e.cb_fired]
            if cbs:
                self._cond.release()
                try:
                    self._fire_callbacks(cbs)
                finally:
                    self._cond.acquire()

    def _group(self, batch: list[_Entry]) -> list:
        """Partition one claimed batch into execution groups
        ``[(batch_fn, entries)]``.  The plain batcher has ONE group —
        its own ``batch_fn`` — so a claim is one device call exactly as
        before.  The shared batcher groups by each entry's carried fn
        (per-tenant model identity): entries sharing a fn coalesce into
        one device call; distinct models run back-to-back inside the
        same dispatcher turn."""
        by_fn: dict = {}
        order = []
        for e in batch:
            k = id(e.fn) if e.fn is not None else 0
            g = by_fn.get(k)
            if g is None:
                g = (e.fn if e.fn is not None else self.batch_fn, [])
                by_fn[k] = g
                order.append(k)
            g[1].append(e)
        return [by_fn[k] for k in order]

    def _run_batch(self, batch: list[_Entry]) -> None:
        """Execute one claimed batch as its execution groups, measuring
        the FULL turn (what the admission estimator predicts).

        Each group's continuous-path callbacks fire the moment THAT
        group's device call returns — before the next group runs.  With
        end-of-turn firing, a multi-model turn made every group-1
        client wait out group-2's device time as pure batch_wait, and
        closed-loop clients locksteped onto whole-turn boundaries
        (measured: 2-tenant QPS@SLO dropped ~25% and p99 grew by a
        full group time).  Runs WITHOUT the lock held."""
        t0 = time.perf_counter()
        for fn, entries in self._group(batch):
            self._exec_group(fn, entries)
            self._fire_callbacks(entries)
        self._turn_s = max(time.perf_counter() - t0, 0.0)

    def _fire_callbacks(self, entries: list[_Entry]) -> None:
        """Book timelines and fire continuous-path callbacks for
        already-executed entries.  Idempotent per entry (``cb_fired``),
        so the leader's end-of-turn sweep can still answer anything a
        BaseException left unfired.  Must be called WITHOUT the lock —
        callbacks enqueue response bytes to the event loop."""
        for e in entries:
            if e.on_done is None or e.cb_fired:
                continue
            e.cb_fired = True
            self._book_timeline(e)
            try:
                e.on_done(e)
            except Exception:
                logger.exception("microbatch completion callback failed")

    def _exec_group(self, fn: Callable, batch: list[_Entry]) -> None:
        """Run one device call; on failure, isolate the blast radius.

        A batched device call is all-or-nothing, so one malformed query
        would otherwise fail every innocent request coalesced with it
        (per-request dispatch isolated such failures).  On a batch of
        >1 failing, re-run each item ALONE: good requests succeed, the
        bad one gets its own exception — same outcomes as unbatched
        serving, paid only on the rare failure path.
        """
        try:
            items = [e.item for e in batch]
            n = len(items)
            if self.pad_batches and n > 1:
                items = items + [items[-1]] * (_pad_size(n) - n)
            t0 = time.perf_counter()
            for e in batch:
                e.t_run0 = t0
            if batch[0].t_claim is not None:
                # accumulation-window cost: first claim -> dispatch
                _m_batch_wait.observe(max(t0 - batch[0].t_claim, 0.0))
            _m_batch_size.observe(float(n))
            with annotate(f"pio.device.batch{len(items)}"):
                results = fn(items)
            t1 = time.perf_counter()
            for e in batch:
                e.t_run1 = t1
            if len(results) != len(items):
                raise RuntimeError(
                    f"batch_fn returned {len(results)} results "
                    f"for {len(items)} items"
                )
            for e, r in zip(batch, results):
                e.value = r
        except Exception as exc:  # noqa: BLE001 — propagate per caller
            if len(batch) == 1:
                batch[0].error = exc
                return
            for e in batch:
                try:
                    (r,) = fn([e.item])
                    e.value = r
                except Exception as solo:  # noqa: BLE001
                    e.error = solo


class SharedBatcher(MicroBatcher):
    """ONE continuous batcher for the whole hive (pio-confluence).

    The pio-hive design gave every tenant a private ``MicroBatcher``:
    under mixed-tenant load, T tenants mean T dispatcher threads each
    coalescing only 1/T of the traffic and competing for the single
    device queue — measured as QPS@SLO(2 tenants) ~1/3 of the
    single-tenant line on the same box.  This class keeps the exact
    claim/run core (one pending queue, one lazily-started dispatcher,
    leader/follower blocking path) and changes WHO gets claimed:

    * **Claim-time weighted deficit round-robin across tenants.**  Each
      claim walks the tenants with pending entries in rotation order;
      every round a tenant's deficit grows by its weight (normalized to
      the largest active weight, floored at ``MIN_SHARE`` so even a
      zero-weighted tenant drains) and each whole unit of deficit buys
      one entry into the batch.  A whale tenant flooding the queue
      therefore claims at most its weighted share per turn while every
      other tenant keeps its own share — starvation-free by
      construction, with FIFO order preserved *within* each tenant.
      A claim with only one tenant pending short-circuits to the plain
      FIFO claim (the solo path pays nothing for the machinery).
    * **Group-keyed execution.**  Claimed entries carry their tenant's
      ``batch_fn``; entries sharing a fn (co-resident same-model
      tenants, or many queries of one tenant) coalesce into ONE padded
      device call, distinct models run back-to-back inside the same
      dispatcher turn — one dispatcher, one device queue walk, no
      cross-tenant thread competition.

    Per-tenant deadline admission, token-bucket quota, and breaker
    checks all stay at enqueue (the registry's ``resolve()`` and the
    serving edge's ``check_admission``) — a query that should shed is
    answered before it ever touches this shared state.

    Weights are PULLED at claim time via per-tenant ``weight_fn``
    callbacks (the serving layer points them at the registry's
    experiment weights), so a hot ``POST /tenants/weights`` update
    reshapes the very next claim with no push plumbing.
    """

    # floor on a tenant's relative claim share: even weight-0 tenants
    # accrue deficit at 1/20 of the heaviest, so nothing queued can be
    # starved and the WDRR loop is bounded (<= 20 rounds per claim)
    MIN_SHARE = 0.05

    def __init__(self, max_batch: int = 64, max_wait_s: float = 0.0,
                 pad_batches: bool = True):
        # no default batch_fn: every entry must carry its tenant's fn
        def _no_fn(items):
            raise RuntimeError(
                "SharedBatcher entries must carry a batch_fn "
                "(submit via a SharedBatcherView)"
            )

        super().__init__(_no_fn, max_batch=max_batch,
                         max_wait_s=max_wait_s, pad_batches=pad_batches)
        # all guarded by _cond, like every other mutable field
        self._weights: dict = {}
        self._weight_fns: dict = {}
        self._reg_counts: dict = {}
        self._deficit: dict = {}
        self._rr: list = []
        self.mixed_batches = 0
        self.tenant_claims: dict = {}

    # -- tenant lifecycle --------------------------------------------------
    def register_tenant(self, tenant, weight: float = 1.0,
                        weight_fn: Optional[Callable] = None) -> None:
        """A view's registration.  Registration counts are per tenant
        key: a reload registers the NEW view before closing the old
        one, and the tenant's scheduling state must survive the
        overlap."""
        with self._cond:
            self._reg_counts[tenant] = self._reg_counts.get(tenant, 0) + 1
            self._weights[tenant] = float(weight)
            if weight_fn is not None:
                self._weight_fns[tenant] = weight_fn
            if tenant not in self._rr:
                self._rr.append(tenant)

    def retire_tenant(self, tenant) -> None:
        """Drop a tenant's scheduling state once its LAST view closes
        (eviction/removal).  Entries it already enqueued still complete
        — they carry their own fn."""
        with self._cond:
            n = self._reg_counts.get(tenant, 0) - 1
            if n > 0:
                self._reg_counts[tenant] = n
                return
            self._reg_counts.pop(tenant, None)
            self._weights.pop(tenant, None)
            self._weight_fns.pop(tenant, None)
            self._deficit.pop(tenant, None)
            if tenant in self._rr:
                self._rr.remove(tenant)

    def set_weights(self, weights: dict) -> None:
        """Push-style weight update (tests / non-registry callers; the
        serving layer uses pull via weight_fn)."""
        with self._cond:
            for t, w in weights.items():
                self._weights[t] = float(w)

    def _weight_of_locked(self, tenant) -> float:
        fn = self._weight_fns.get(tenant)
        if fn is not None:
            try:
                w = float(fn())
                if w > 0.0:
                    return w
            except Exception:
                logger.exception("weight_fn for tenant %r failed", tenant)
        w = self._weights.get(tenant, 1.0)
        return w if w > 0.0 else 0.0

    # -- claim policy ------------------------------------------------------
    def _claim_locked(self) -> list[_Entry]:
        pend = self._pending
        if not pend:
            return []
        by_tenant: dict = {}
        order: list = []
        for e in pend:
            q = by_tenant.get(e.tenant)
            if q is None:
                q = by_tenant[e.tenant] = []
                order.append(e.tenant)
            q.append(e)
        if len(by_tenant) == 1:
            # solo-tenant claim: plain FIFO, zero WDRR overhead (the
            # single-tenant server and idle-hive case)
            batch = super()._claim_locked()
            if batch:
                _m_tenants_per_batch.observe(1.0)
                t0 = batch[0].tenant
                self.tenant_claims[t0] = (
                    self.tenant_claims.get(t0, 0) + len(batch)
                )
            return batch
        # rotation order: persistent registration order, rotated one
        # step per claim so no tenant permanently goes first; tenants
        # that only appear in the queue (e.g. already-retired) append
        for t in order:
            if t not in self._rr:
                self._rr.append(t)
        walk = [t for t in self._rr if t in by_tenant]
        # weights normalized to the largest ACTIVE weight, floored —
        # the round count per claim is bounded by 1/MIN_SHARE
        weights = {t: self._weight_of_locked(t) for t in walk}
        wmax = max(weights.values()) or 1.0
        share = {
            t: max(weights[t] / wmax, self.MIN_SHARE) for t in walk
        }
        deficit = self._deficit
        batch: list[_Entry] = []
        room = self.max_batch
        while room > 0 and any(by_tenant[t] for t in walk):
            for t in walk:
                q = by_tenant[t]
                if not q:
                    # classic DRR: an empty queue forfeits its deficit
                    # (banked credit would burst later, not smooth)
                    deficit.pop(t, None)
                    continue
                d = deficit.get(t, 0.0) + share[t]
                while q and room > 0 and d >= 1.0:
                    batch.append(q.pop(0))
                    d -= 1.0
                    room -= 1
                deficit[t] = d
                if room <= 0:
                    break
        # remove claimed entries from pending, preserving FIFO order
        claimed = {id(e) for e in batch}
        self._pending = [e for e in pend if id(e) not in claimed]
        now = time.perf_counter()
        tenants_seen = set()
        for e in batch:
            e.t_claim = now
            tenants_seen.add(e.tenant)
            self.tenant_claims[e.tenant] = (
                self.tenant_claims.get(e.tenant, 0) + 1
            )
        if len(tenants_seen) > 1:
            self.mixed_batches += 1
        if batch:
            _m_tenants_per_batch.observe(float(len(tenants_seen)))
        if self._rr:
            self._rr.append(self._rr.pop(0))
        _m_queue_depth.set(float(len(self._pending)))
        return batch

    # -- observability -----------------------------------------------------
    def reset_stats(self) -> None:
        super().reset_stats()
        with self._cond:
            self.mixed_batches = 0
            self.tenant_claims = {}

    def stats(self) -> dict:
        out = super().stats()
        with self._cond:
            out["shared"] = True
            out["tenantsRegistered"] = len(self._reg_counts)
            out["mixedBatches"] = self.mixed_batches
            out["tenantClaims"] = {
                ("/".join(str(p) for p in k) if isinstance(k, tuple)
                 else str(k)): v
                for k, v in self.tenant_claims.items()
            }
        return out


class SharedBatcherView:
    """One tenant's handle on the process-wide :class:`SharedBatcher`.

    Exposes the exact surface the serving edges and benches already
    use on a private ``MicroBatcher`` (``submit`` / ``submit_nowait`` /
    ``check_admission`` / ``estimate_wait_s`` / ``stats`` /
    ``batch_fn`` / ``close``), stamping every entry with the tenant key
    and the tenant's own ``batch_fn``.  ``close()`` retires only THIS
    tenant's scheduling state — in-flight entries complete on the fn
    they carry, and the shared core (and its dispatcher) lives until
    the server stops."""

    __slots__ = ("core", "tenant", "batch_fn", "_closed")

    def __init__(self, core: SharedBatcher, tenant, batch_fn: Callable,
                 weight: float = 1.0,
                 weight_fn: Optional[Callable] = None):
        self.core = core
        self.tenant = tenant
        self.batch_fn = batch_fn
        self._closed = False
        core.register_tenant(tenant, weight=weight, weight_fn=weight_fn)

    @property
    def max_batch(self) -> int:
        return self.core.max_batch

    @property
    def pad_batches(self) -> bool:
        return self.core.pad_batches

    def estimate_wait_s(self) -> float:
        return self.core.estimate_wait_s()

    def check_admission(self, deadline: Optional[Deadline]) -> None:
        self.core.check_admission(deadline)

    def stats(self) -> dict:
        out = self.core.stats()
        out["tenant"] = str(self.tenant)
        return out

    def reset_stats(self) -> None:
        self.core.reset_stats()

    def submit(self, item: Any,
               deadline: Optional[Deadline] = None) -> Any:
        if self._closed:
            raise RuntimeError("batcher is closed")
        return self.core.submit(item, deadline=deadline,
                                tenant=self.tenant, fn=self.batch_fn)

    def submit_nowait(self, item: Any, on_done: Callable,
                      deadline: Optional[Deadline] = None,
                      timeline=None) -> None:
        # closed-view submits raise the same RuntimeError a closed
        # MicroBatcher does: the event-loop edge's reload-retry path
        # keys on it
        if self._closed:
            raise RuntimeError("batcher is closed")
        self.core.submit_nowait(item, on_done, deadline=deadline,
                                timeline=timeline, tenant=self.tenant,
                                fn=self.batch_fn)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.core.retire_tenant(self.tenant)
