"""Continuous micro-batching for the serving hot path.

The reference detaches one JVM actor per request
(`workflow/CreateServer.scala:437,464`) and each predict is cheap CPU
work, so concurrency alone scales it.  Here every predict is a device
call, and a TPU has ONE execution queue: N concurrent requests that
each dispatch their own top-k matmul serialize on the queue, so
per-request latency grows ~linearly with concurrency while aggregate
QPS stays flat (measured: 8 threads take p50 from ~1 ms to ~7.5 ms at
unchanged QPS, bench_serving.py --threads).

The TPU-shaped fix is to make concurrency *wider, not deeper*: coalesce
the queries that arrive while a device call is in flight into ONE
batched call (`Algorithm.batch_predict` — a [B, R] x [R, M] matmul
costs barely more than the [R] x [R, M] one).  Two submission paths
share one pending queue and one claim/run core:

* **Blocking** ``submit(x)`` — the original leader/follower pattern:
  a request appends its query; if no batch is executing (and no
  dispatcher owns the queue), it becomes the LEADER and runs the batch
  on its own thread; requests arriving meanwhile park as FOLLOWERS.
  Under no concurrency this degenerates to a direct call — no extra
  thread, no timer, zero added latency.
* **Continuous** ``submit_nowait(x, on_done, ...)`` (pio-surge) — the
  event-loop edge admits requests *into the in-flight queue as they
  arrive* and returns immediately; a lazily-started dispatcher thread
  claims whatever is pending the moment the device frees up and fires
  per-entry completion callbacks.  No thread ever parks per request:
  the edge stays one loop thread + one dispatcher regardless of
  concurrency.

Deadline-aware admission (pio-surge): entries may carry a
``resilience.policy.Deadline``.  A claimed entry already past its
deadline is completed with ``DeadlineExceeded`` WITHOUT ever reaching
the device (the device queue is the one resource concurrency shares —
work for a client that gave up is pure stolen capacity), and
:meth:`MicroBatcher.estimate_wait_s` exposes an EWMA-based estimate of
queue+service time so the serving edge can reject a request that
cannot make its SLO *up front* as a structured 503
(:class:`AdmissionRejected`) rather than queue it to die.

Batch size therefore adapts to the arrival rate with no tuning knob
doing latency/throughput trades behind the operator's back
(``max_wait_s`` exists for completeness but defaults to 0).

Determinism note: a batched matmul compiles per batch size, so the same
query served inside different batch compositions can differ at float
ulp scale (different reduction order) — rankings are stable, scores may
wobble ~1e-7.  Deployments that need bitwise per-request determinism
set ``ServerConfig(microbatch="off")``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional, Sequence

from ..obs.timeline import (
    MICROBATCH_ADMISSION_TOTAL,
    MICROBATCH_BATCH_SIZE,
    MICROBATCH_QUEUE_DEPTH,
    MICROBATCH_ROLE_TOTAL,
    MICROBATCH_WAIT_SECONDS,
    annotate,
    current_timeline,
)
from ..resilience.policy import Deadline, DeadlineExceeded

__all__ = [
    "AdmissionRejected",
    "EwmaEstimator",
    "MicroBatcher",
    "dispatchable_sizes",
]

logger = logging.getLogger(__name__)

# pulse saturation metrics, children cached at import (labels() is too
# hot for the per-submit path); process-wide like pio_query_latency —
# one serving process hosts one live batcher
_m_queue_depth = MICROBATCH_QUEUE_DEPTH.child()
_m_batch_size = MICROBATCH_BATCH_SIZE.child()
_m_batch_wait = MICROBATCH_WAIT_SECONDS.child()
_m_leader = MICROBATCH_ROLE_TOTAL.labels(role="leader")
_m_follower = MICROBATCH_ROLE_TOTAL.labels(role="follower")
_m_dispatched = MICROBATCH_ROLE_TOTAL.labels(role="dispatched")
_m_adm_rejected = MICROBATCH_ADMISSION_TOTAL.labels(outcome="rejected")
_m_adm_expired = MICROBATCH_ADMISSION_TOTAL.labels(outcome="expired")

# distinguishes "no result produced" from a legitimate None result —
# batch_fns whose valid outputs include None must not have them
# clobbered by the leader-abort guard
_UNSET = object()


class AdmissionRejected(DeadlineExceeded):
    """The serving edge refused to queue a request that could not make
    its deadline (estimated queue+service time exceeds the remaining
    budget).  A subclass of :class:`DeadlineExceeded` so every existing
    503 path handles it; kept distinct so the edge can count sheds
    separately from in-flight expiries."""


class EwmaEstimator:
    """Exponentially-weighted moving average of observed durations —
    the memory behind deadline-aware admission, shared by the
    micro-batcher (device-batch service time) and the fleet router
    (replica round-trip time; pio-scout satellite).  ``0.0`` until the
    first observation, so a cold estimator never sheds: no evidence
    means admit.  Not synchronized itself — callers serialize
    observations (the batcher under its condition variable, the router
    under its round-robin lock)."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.25):
        self.alpha = alpha
        self.value = 0.0

    def observe(self, dt: float) -> None:
        self.value = (
            dt if self.value <= 0.0
            else self.alpha * dt + (1.0 - self.alpha) * self.value
        )

    def estimate(self) -> float:
        return self.value


def _pad_size(n: int) -> int:
    """The batch size ``n`` items actually dispatch as under pow2
    padding — THE definition; the warmup ladder derives from it."""
    return 1 << (n - 1).bit_length() if n > 1 else 1


def dispatchable_sizes(max_batch: int) -> list[int]:
    """Every batch size a padding batcher with this ``max_batch`` can
    dispatch: 1, 2, 4, ..., _pad_size(max_batch).  Template warmups
    build their compile ladders from THIS (templates/_common.pow2_ladder
    delegates here) so a change to the padding scheme cannot silently
    desynchronize warmup from dispatch.

    ``max_batch <= 0`` means "no batcher at all" (serving passes 0 when
    micro-batching is off or auto-gated off): the ladder is EMPTY —
    every request then runs the per-query predict path, and compiling
    batched executables would be pure wasted XLA work at deploy/reload."""
    if max_batch <= 0:
        return []
    top = _pad_size(max_batch)
    b, sizes = 1, []
    while b <= top:
        sizes.append(b)
        b <<= 1
    return sizes


class _Entry:
    # t_enq/t_claim/t_run0/t_run1 are the pulse timeline stamps: set by
    # whichever thread performs the transition (enqueue by the caller,
    # claim by the leader/dispatcher, run bracketing by the executing
    # thread) and read AFTER ``done`` — the condition variable's
    # release/acquire (blocking path) or the dispatcher's post-batch
    # callback (continuous path) orders the writes before the read
    __slots__ = ("item", "done", "value", "error", "deadline", "tl",
                 "on_done", "t_enq", "t_claim", "t_run0", "t_run1")

    def __init__(self, item, deadline: Optional[Deadline] = None,
                 tl=None, on_done: Optional[Callable] = None):
        self.item = item
        self.done = False
        self.value = _UNSET
        self.error: Exception | None = None
        self.deadline = deadline
        self.tl = tl
        self.on_done = on_done
        self.t_enq = time.perf_counter()
        self.t_claim = None
        self.t_run0 = None
        self.t_run1 = None


class MicroBatcher:
    """Coalesce concurrent ``submit(x)`` / ``submit_nowait(x, cb)``
    calls into ``batch_fn([x...])``.

    ``batch_fn`` receives a list of items and must return a list of
    results of the same length and order.  An exception from
    ``batch_fn`` fails every request in that batch (callers see the
    same exception a direct call would have raised).
    """

    def __init__(
        self,
        batch_fn: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_wait_s: float = 0.0,
        pad_batches: bool = False,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        # pad each batch to the next power of two by repeating the last
        # item (results sliced off).  An XLA batch_fn compiles ONE
        # executable per distinct batch size; continuous batching
        # naturally produces every size 1..max_batch, which would pay a
        # compile mid-traffic for each new size — measured as a p99
        # spike on first exposure to load.  Padding bounds the
        # executable count to log2(max_batch)+1.  Only valid when
        # batch_fn is a pure per-item map (duplicated trailing items
        # must be harmless), which predicts are.
        self.pad_batches = pad_batches
        self._cond = threading.Condition()
        self._pending: list[_Entry] = []
        self._running = False
        self._closed = False
        self._dispatcher_alive = False
        # EWMA of recent device-batch service time: the admission
        # estimator's input.  Seeded 0 (= "no evidence, admit"), so a
        # cold batcher never sheds; mutated only under _cond.
        self._ewma = EwmaEstimator()
        # observability: how the batcher is actually coalescing.
        # Mutated only under _cond; read through stats() (bare reads
        # tore under concurrency — serving status JSON and the benches
        # all go through the locked snapshot now)
        self.batches = 0
        self.requests = 0
        self.max_seen = 0
        self.leaders = 0
        self.followers = 0
        self.dispatched = 0
        self.expired = 0

    def reset_stats(self) -> None:
        with self._cond:
            self.batches = self.requests = self.max_seen = 0
            self.leaders = self.followers = 0
            self.dispatched = self.expired = 0

    def stats(self) -> dict:
        """Locked snapshot of the coalescing counters plus the live
        queue depth — the ONE way to read them (status JSON, benches,
        /pulse.html)."""
        with self._cond:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "maxBatchSeen": self.max_seen,
                "leaders": self.leaders,
                "followers": self.followers,
                "dispatched": self.dispatched,
                "expired": self.expired,
                "queueDepth": len(self._pending),
                "dispatcher": self._dispatcher_alive,
                "ewmaBatchSec": self._ewma.value,
            }

    # -- admission (pio-surge) ---------------------------------------------
    def estimate_wait_s(self) -> float:
        """Estimated queue + service time a request admitted NOW would
        experience: (in-flight batch + queued batches ahead + its own
        batch) x the EWMA batch service time.  0.0 until the first
        batch completes — no evidence means admit, never shed."""
        with self._cond:
            ew = self._ewma.value
            if ew <= 0.0:
                return 0.0
            ahead = 1.0 if self._running else 0.0
            ahead += len(self._pending) / float(self.max_batch)
            return (ahead + 1.0) * ew

    def check_admission(self, deadline: Optional[Deadline]) -> None:
        """Raise :class:`AdmissionRejected` when ``deadline`` cannot be
        met even optimistically.  The up-front half of deadline-aware
        admission: a request the estimator already knows will die in
        the queue is answered a structured 503 NOW, costing the client
        one RTT instead of its full timeout."""
        if deadline is None:
            return
        remaining = deadline.remaining()
        if remaining <= 0.0:
            _m_adm_rejected.inc()
            raise AdmissionRejected(
                f"query deadline already exceeded its "
                f"{deadline.budget_s:.3f}s budget at admission"
            )
        est = self.estimate_wait_s()
        if est > remaining:
            _m_adm_rejected.inc()
            raise AdmissionRejected(
                f"estimated queue+service time {est * 1e3:.1f}ms exceeds "
                f"the {remaining * 1e3:.1f}ms remaining of the "
                f"{deadline.budget_s:.3f}s deadline"
            )

    # -- submission paths --------------------------------------------------
    def submit(self, item: Any,
               deadline: Optional[Deadline] = None) -> Any:
        """Blocking submit: returns the result (or raises) on the
        calling thread.  With no dispatcher running, the classic
        leader/follower flow; with one, the caller parks as a follower
        of the dispatcher's batches."""
        entry = _Entry(item, deadline=deadline)
        led_own = False
        with self._cond:
            self._pending.append(entry)
            _m_queue_depth.set(float(len(self._pending)))
            # wake a leader/dispatcher sitting in its accumulation
            # window (no-op for followers: they re-check and wait)
            self._cond.notify_all()
            while True:
                if entry.done:
                    break
                if not self._running and not self._dispatcher_alive:
                    # become the leader for everything pending now
                    self._running = True
                    batch = self._claim_locked()
                    # role bookkeeping: with > max_batch entries ahead,
                    # the claimed batch may not include our own entry —
                    # then we led for OTHERS and our request is still a
                    # follower of some later batch
                    if any(e is entry for e in batch):
                        led_own = True
                    self._lead(batch)
                    continue  # re-check: our entry is done (we led it)
                self._cond.wait()
            if led_own:
                self.leaders += 1
            else:
                self.followers += 1
        (_m_leader if led_own else _m_follower).inc()
        # credit the caller's pulse timeline with what this entry
        # actually experienced (error requests decompose too)
        self._book_timeline(entry)
        if entry.error is not None:
            raise entry.error
        return entry.value if entry.value is not _UNSET else None

    def submit_nowait(self, item: Any, on_done: Callable[["_Entry"], None],
                      deadline: Optional[Deadline] = None,
                      timeline=None) -> None:
        """Continuous (callback) submit: the entry is admitted into the
        pending queue immediately and ``on_done(entry)`` fires — on the
        dispatcher thread, after the entry's timeline is booked — once
        ``entry.value``/``entry.error`` is set.  The lazily-started
        dispatcher claims the next batch the moment the device frees
        up, so arrivals ride the NEXT device call rather than waiting
        out a batch boundary."""
        entry = _Entry(item, deadline=deadline, tl=timeline,
                       on_done=on_done)
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if not self._dispatcher_alive:
                self._dispatcher_alive = True
                threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    name="microbatch-dispatch",
                ).start()
            self._pending.append(entry)
            _m_queue_depth.set(float(len(self._pending)))
            self._cond.notify_all()

    def close(self) -> None:
        """Stop accepting ``submit_nowait`` work and let the dispatcher
        drain what is pending, then exit.  Blocking ``submit`` keeps
        working (self-led) — a reload swaps batchers while in-flight
        queries still hold the old one."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- claim/run core (shared by leaders and the dispatcher) -------------
    def _claim_locked(self) -> list[_Entry]:
        batch = self._pending[: self.max_batch]
        del self._pending[: len(batch)]
        now = time.perf_counter()
        for e in batch:
            e.t_claim = now
        _m_queue_depth.set(float(len(self._pending)))
        return batch

    def _dispatch_loop(self) -> None:
        """Standing leader for the continuous path: claims pending
        entries whenever the device is free.  Blocking submitters
        coalesce into its batches as followers."""
        with self._cond:
            try:
                while True:
                    while not self._pending and not self._closed:
                        self._cond.wait()
                    if not self._pending and self._closed:
                        break
                    if self._running:
                        # a blocking leader beat us to the claim
                        self._cond.wait()
                        continue
                    self._running = True
                    batch = self._claim_locked()
                    try:
                        self._lead(batch)
                    except Exception:
                        # _lead's finally already completed the batch;
                        # the dispatcher itself must survive (a dead
                        # dispatcher would wedge every future submit)
                        logger.exception("microbatch dispatcher error")
            finally:
                self._dispatcher_alive = False
                self._cond.notify_all()

    def _book_timeline(self, entry: _Entry) -> None:
        """Book queue_wait/batch_wait/device from the entry stamps onto
        the entry's attached timeline (continuous path) or the calling
        thread's current one (blocking path).  Residual time inside the
        covered region (condition wake latency, a solo retry after a
        failed batch) is attributed to ``device`` by add_block, so the
        timeline's segment sum still equals wall time."""
        tl = entry.tl if entry.tl is not None else current_timeline()
        if tl is None:
            return
        parts = []
        if entry.t_claim is not None:
            parts.append(("queue_wait", entry.t_claim - entry.t_enq))
            if entry.t_run0 is not None:
                parts.append(("batch_wait", entry.t_run0 - entry.t_claim))
                if entry.t_run1 is not None:
                    parts.append(("device", entry.t_run1 - entry.t_run0))
        tl.add_block(parts, residual_to="device")

    def _lead(self, batch: list[_Entry]) -> None:
        """Run one claimed batch on the calling thread.  Called with
        the lock HELD; releases it around the device call (and around
        continuous-path callbacks) and re-acquires.

        Claim-time deadline enforcement happens here: entries already
        past their deadline are completed with ``DeadlineExceeded`` and
        never reach the device.

        The ENTIRE leader turn — accumulation window included — sits
        inside one try/finally: a BaseException landing anywhere in it
        (``Condition.wait`` re-acquires the lock before raising, so the
        lock state is consistent) must still mark every claimed entry
        done and clear ``_running``, or the followers block forever and
        every future ``submit`` hangs behind a leaderless batcher."""
        completed = False
        live: list[_Entry] = []
        n_expired = 0
        for e in batch:
            if e.deadline is not None and e.deadline.expired:
                e.error = DeadlineExceeded(
                    f"query expired in the batch queue after "
                    f"{time.perf_counter() - e.t_enq:.3f}s (budget "
                    f"{e.deadline.budget_s:.3f}s); never dispatched"
                )
                n_expired += 1
            else:
                live.append(e)
        if n_expired:
            _m_adm_expired.inc(n_expired)
        try:
            if self.max_wait_s > 0 and live and len(live) < self.max_batch:
                # optional accumulation window (off by default): give
                # near-simultaneous arrivals a chance to join this batch.
                # Arrivals notify; absorb after EVERY wake (timeout
                # included) so nothing queued during the window is left
                # behind for the next leader.
                deadline = time.monotonic() + self.max_wait_s
                while len(live) < self.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                    take = self.max_batch - len(live)
                    absorbed = self._pending[:take]
                    del self._pending[:take]
                    if absorbed:
                        now = time.perf_counter()
                        for e in absorbed:
                            e.t_claim = now
                        live += absorbed
                        batch += absorbed
                        _m_queue_depth.set(float(len(self._pending)))
            if live:
                self._cond.release()
                try:
                    self._run_batch(live)
                finally:
                    self._cond.acquire()
            completed = True
        finally:
            for e in batch:
                if not completed and e.value is _UNSET and e.error is None:
                    # a BaseException (KeyboardInterrupt/SystemExit) tore
                    # through the leader: _run_batch's except clause only
                    # handles Exception, so coalesced followers would
                    # otherwise wake with value=None and serve garbage.
                    # The interrupt propagates to the leader's caller;
                    # followers re-raise this instead.
                    e.error = RuntimeError(
                        "batch leader aborted before producing results"
                    )
                e.done = True
            self._running = False
            if live:
                self.batches += 1
                self.max_seen = max(self.max_seen, len(live))
                e0 = live[0]
                if e0.t_run0 is not None and e0.t_run1 is not None:
                    self._ewma.observe(max(e0.t_run1 - e0.t_run0, 0.0))
            self.requests += len(batch)
            self.expired += n_expired
            # continuous entries get the third role: the dispatcher ran
            # the device call for them, no request thread led anything
            n_disp = sum(1 for e in batch if e.on_done is not None)
            if n_disp:
                self.dispatched += n_disp
                _m_dispatched.inc(n_disp)
            self._cond.notify_all()
            # continuous-path completions: book timelines and fire the
            # callbacks OUTSIDE the lock (a callback enqueues response
            # bytes to the event loop / runs serving.serve — neither
            # may hold the batcher's condition).  Inside the finally so
            # even a BaseException tearing through the leader still
            # answers every event-loop request (their entries carry the
            # leader-abort error by this point).
            cbs = [e for e in batch if e.on_done is not None]
            if cbs:
                self._cond.release()
                try:
                    for e in cbs:
                        self._book_timeline(e)
                        try:
                            e.on_done(e)
                        except Exception:
                            logger.exception(
                                "microbatch completion callback failed"
                            )
                finally:
                    self._cond.acquire()

    def _run_batch(self, batch: list[_Entry]) -> None:
        """Execute one batch; on failure, isolate the blast radius.

        A batched device call is all-or-nothing, so one malformed query
        would otherwise fail every innocent request coalesced with it
        (per-request dispatch isolated such failures).  On a batch of
        >1 failing, re-run each item ALONE: good requests succeed, the
        bad one gets its own exception — same outcomes as unbatched
        serving, paid only on the rare failure path.
        """
        try:
            items = [e.item for e in batch]
            n = len(items)
            if self.pad_batches and n > 1:
                items = items + [items[-1]] * (_pad_size(n) - n)
            t0 = time.perf_counter()
            for e in batch:
                e.t_run0 = t0
            if batch[0].t_claim is not None:
                # accumulation-window cost: first claim -> dispatch
                _m_batch_wait.observe(max(t0 - batch[0].t_claim, 0.0))
            _m_batch_size.observe(float(n))
            with annotate(f"pio.device.batch{len(items)}"):
                results = self.batch_fn(items)
            t1 = time.perf_counter()
            for e in batch:
                e.t_run1 = t1
            if len(results) != len(items):
                raise RuntimeError(
                    f"batch_fn returned {len(results)} results "
                    f"for {len(items)} items"
                )
            for e, r in zip(batch, results):
                e.value = r
        except Exception as exc:  # noqa: BLE001 — propagate per caller
            if len(batch) == 1:
                batch[0].error = exc
                return
            for e in batch:
                try:
                    (r,) = self.batch_fn([e.item])
                    e.value = r
                except Exception as solo:  # noqa: BLE001
                    e.error = solo
