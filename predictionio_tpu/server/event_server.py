"""REST Event Server (ingestion API, default port 7070).

Re-expression of reference `data/api/EventAPI.scala:90-469` on the stdlib
threading HTTP server.  Routes + semantics parity:

* ``POST /events.json?accessKey=K[&channel=C]``  -> 201 ``{"eventId": ...}``
* ``POST /batch/events.json``                    -> per-event status list
* ``GET  /events.json?accessKey=K&...filters``   -> event list (find filters:
  startTime, untilTime, entityType, entityId, event, targetEntityType,
  targetEntityId, limit, reversed)
* ``GET|DELETE /events/<id>.json?accessKey=K``
* ``GET  /stats.json?accessKey=K``               (when stats enabled)
* ``POST /webhooks/<name>.json`` / ``.form``, ``GET`` probes
* ``GET  /``                                      -> server info

Auth: accessKey (query param) -> (appId, channelId); keys may whitelist
event names (`AccessKeys.scala:27-54`).  401 on bad key, 400 on invalid
payloads, 404 on unknown ids/channels — matching the reference's
rejection handler (`api/Common.scala`).
"""

from __future__ import annotations

import json
import logging
import sqlite3
import threading
import time
import urllib.parse
from typing import Any, Optional

from ..obs import (
    EVENT_WRITE_LATENCY,
    INGEST_SHARD_UNAVAILABLE_TOTAL,
    fleet,
    get_tracer,
    scope,
    timeline,
    trace_scope,
)
from ..resilience import faults
from ..resilience.policy import RetryPolicy
from ..storage.event import (
    Event,
    EventValidationError,
    new_event_id,
    new_event_ids,
    parse_time,
)
from ..storage.levents import NO_TARGET, ShardUnavailableError
from ..storage.registry import Storage, get_storage
from ..storage.sqlite_events import event_to_row
from ..storage.wal import GroupCommitWAL
from .http_base import HTTPServerBase, JsonRequestHandler
from .stats import StatsCollector
from .webhooks import (
    FORM_CONNECTORS,
    JSON_CONNECTORS,
    ConnectorError,
    to_event,
)

logger = logging.getLogger(__name__)

__all__ = ["EventServer", "EventServerConfig"]


class EventServerConfig:
    def __init__(self, host: str = "127.0.0.1", port: int = 7070,
                 stats: bool = True, write_retries: int = 3,
                 write_backoff_s: float = 0.05,
                 retry_seed: Optional[int] = None,
                 max_connections: int = 512,
                 wal_dir: Optional[str] = None,
                 wal_commit_interval_s: float = 0.02,
                 wal_fsync: bool = True,
                 owned_shards: Optional[list[int]] = None,
                 ttl_s: Optional[float] = None,
                 compact_interval_s: Optional[float] = None,
                 maintenance_interval_s: float = 30.0,
                 slo_ms: Optional[float] = None):
        self.host = host
        self.port = port
        self.stats = stats
        # concurrent-connection cap (pio-surge): attempts past it get a
        # structured 503 + close instead of one pinned thread each
        self.max_connections = max_connections
        # transient-storage-failure policy: a busy WAL / locked sqlite
        # write is retried with backoff before the route answers
        # 503 + Retry-After (write_retries counts the first try)
        self.write_retries = write_retries
        self.write_backoff_s = write_backoff_s
        self.retry_seed = retry_seed
        # pio-levee ingest WAL: when set, writes group-commit through
        # `storage.wal.GroupCommitWAL` (ack = WAL fsync, sqlite commits
        # drain in the background; crash replay on next boot)
        self.wal_dir = wal_dir
        self.wal_commit_interval_s = wal_commit_interval_s
        self.wal_fsync = wal_fsync
        # shard-owner worker mode: restrict writes (and WAL files) to a
        # fixed shard subset; None = own everything (single process)
        self.owned_shards = owned_shards
        # bounded live window: purge events older than ttl_s, compact
        # the owned shard files every compact_interval_s (both off by
        # default; the maintenance thread only runs when one is set)
        self.ttl_s = ttl_s
        self.compact_interval_s = compact_interval_s
        self.maintenance_interval_s = maintenance_interval_s
        # ingest write-latency SLO (ms): arms pio_slo_burn_rate{window}
        # on the event-write histogram, the same multi-window burn
        # gauges the serving edge carries (pio-sentry)
        self.slo_ms = slo_ms


class AuthError(Exception):
    pass


# storage exceptions worth retrying: cross-connection sqlite contention
# (SQLITE_BUSY past the busy_timeout, WAL checkpoint races) is transient
# by construction; schema/constraint errors are not OperationalError
TRANSIENT_STORAGE_ERRORS = (sqlite3.OperationalError,)


class EventServer(HTTPServerBase):
    server_name = "events"
    def __init__(self, storage: Optional[Storage] = None,
                 config: Optional[EventServerConfig] = None):
        self.storage = storage or get_storage()
        self.config = config or EventServerConfig()
        self.stats = StatsCollector() if self.config.stats else None
        self.write_retry = RetryPolicy(
            max_attempts=self.config.write_retries,
            base_s=self.config.write_backoff_s,
            cap_s=max(1.0, self.config.write_backoff_s * 10),
            seed=self.config.retry_seed,
        )
        es = self.storage.get_event_store()
        if (self.config.owned_shards is not None
                and hasattr(es, "set_owned_shards")):
            es.set_owned_shards(self.config.owned_shards)
        self.wal: Optional[GroupCommitWAL] = None
        if self.config.wal_dir:
            self.wal = GroupCommitWAL(
                es, self.config.wal_dir,
                owned_shards=self.config.owned_shards,
                commit_interval_s=self.config.wal_commit_interval_s,
                fsync=self.config.wal_fsync,
            )
        # channels this process has written — the TTL/compaction
        # maintenance scope (a set mutated under the GIL only; readers
        # snapshot with list())
        self._seen_channels: set[tuple[int, int]] = set()
        self._maint_stop = threading.Event()
        self._maint_thread: Optional[threading.Thread] = None
        if self.config.ttl_s or self.config.compact_interval_s:
            self._maint_thread = threading.Thread(
                target=self._maintenance_loop,
                name="events-maintenance", daemon=True,
            )
            self._maint_thread.start()
        # pio-sentry on the write edge: --slo-ms arms the multi-window
        # burn-rate gauges over the event-write latency histogram
        self._burn = None
        if self.config.slo_ms:
            self._burn = fleet.install_burn_rate(
                EVENT_WRITE_LATENCY.child(), self.config.slo_ms / 1e3,
            )
        scope.ensure_started()

    def _note_retry(self, kind: str):
        def on_retry(attempt: int, exc: BaseException) -> None:
            logger.warning("%s retry %d after %s", kind, attempt, exc)
            if self.stats is not None:
                self.stats.note(f"{kind}.retry")
        return on_retry

    def barrier(self) -> None:
        """Read-your-writes: drain the ingest WAL's commit backlog so a
        201 is visible to this server's own GET routes.  No-op without
        a WAL; a stuck drain raises the transient-storage surface."""
        if self.wal is not None:
            self.wal.barrier()

    def stop(self) -> None:
        super().stop()
        self._maint_stop.set()
        if self._maint_thread is not None:
            self._maint_thread.join(timeout=5.0)
            self._maint_thread = None
        if self.wal is not None:
            self.wal.close()
            self.wal = None

    def _maintenance_loop(self) -> None:
        """Time-windowed retention: TTL purge each tick, compaction on
        its own (longer) cadence — both scoped to owned shards so a
        worker never takes a sibling's writer lock."""
        scope.register_thread_role("events_maintenance")
        next_compact = time.monotonic() + (
            self.config.compact_interval_s or float("inf")
        )
        while not self._maint_stop.wait(self.config.maintenance_interval_s):
            es = self.storage.get_event_store()
            try:
                if self.config.ttl_s and hasattr(es, "purge_older_than"):
                    cutoff = int((time.time() - self.config.ttl_s) * 1000)
                    for app_id, ch in list(self._seen_channels):
                        n = es.purge_older_than(cutoff, app_id, ch)
                        if n:
                            logger.info(
                                "TTL purge: %d events older than %ss "
                                "(app %d, channel %d)",
                                n, self.config.ttl_s, app_id, ch,
                            )
                            if self.stats is not None:
                                self.stats.note("ttl.purged", n)
                if (self.config.compact_interval_s
                        and time.monotonic() >= next_compact):
                    next_compact = (time.monotonic()
                                    + self.config.compact_interval_s)
                    # drain first: VACUUM wants the writer lock the WAL
                    # committer would otherwise be using
                    self.barrier()
                    es.compact()
                    logger.info("compacted event store")
            except Exception:
                # retention is advisory; a failed pass must not kill
                # the thread (the next tick retries)
                logger.exception("event-store maintenance pass failed")

    @property
    def host(self) -> str:
        return self.config.host

    @property
    def port(self) -> int:
        return self.config.port

    @property
    def max_connections(self) -> int:
        return self.config.max_connections

    @port.setter
    def port(self, v: int) -> None:
        self.config.port = v

    # -- auth (EventAPI.scala:90-116) -------------------------------------
    def authenticate(self, params: dict[str, list[str]]) -> tuple[int, int, list[str]]:
        """accessKey [+ channel] -> (app_id, channel_id, allowed_events)."""
        keys = params.get("accessKey")
        if not keys or not keys[0]:
            raise AuthError("missing accessKey")
        md = self.storage.get_metadata()
        ak = md.access_key_get(keys[0])
        if ak is None:
            raise AuthError("invalid accessKey")
        channel_id = 0
        channels = params.get("channel")
        if channels and channels[0]:
            chans = md.channel_get_by_app(ak.appid)
            match = [c for c in chans if c.name == channels[0]]
            if not match:
                raise AuthError(f"invalid channel {channels[0]!r}")
            channel_id = match[0].id
        return ak.appid, channel_id, ak.events

    # -- handlers ----------------------------------------------------------
    @staticmethod
    def check_allowed(event: Event, allowed: list[str]) -> None:
        """Access-key event whitelist (`AccessKeys.scala:27-54`); one
        definition for the single-event and batch routes."""
        if allowed and event.event not in allowed:
            raise AuthError(
                f"accessKey is not allowed to write event {event.event!r}"
            )

    def insert_event(self, event: Event, app_id: int, channel_id: int,
                     allowed: list[str]) -> str:
        self.check_allowed(event, allowed)
        es = self.storage.get_event_store()
        es.init_channel(app_id, channel_id)
        self._seen_channels.add((app_id, channel_id))

        if self.wal is not None:
            # group-commit path: ack = WAL fsync; the sqlite commit
            # drains in the background.  ShardUnavailableError is NOT
            # transient (sticky until restart/recovery) so the retry
            # policy passes it straight through to the 503 route.
            def put():
                faults.check("storage.write")
                eid = event.event_id or new_event_id()
                self.wal.submit(
                    app_id, channel_id, [event_to_row(event, eid)]
                )
                return eid
        else:
            def put():
                faults.check("storage.write")
                return es.insert(event, app_id, channel_id)

        # span + histogram cover the whole retried write: the client's
        # view of how long ingestion held their request
        t0 = time.perf_counter()
        try:
            return self.write_retry.call(
                put, retry_on=TRANSIENT_STORAGE_ERRORS,
                on_retry=self._note_retry("storage.write"),
            )
        finally:
            dt = time.perf_counter() - t0
            EVENT_WRITE_LATENCY.child().observe(dt)
            get_tracer().record("events.write", dt,
                                attrs={"event": event.event})

    @staticmethod
    def _find_kwargs(params: dict[str, list[str]]) -> dict[str, Any]:
        def one(name):
            v = params.get(name)
            return v[0] if v else None

        kw: dict[str, Any] = {}
        if one("startTime"):
            kw["start_time"] = parse_time(one("startTime"))
        if one("untilTime"):
            kw["until_time"] = parse_time(one("untilTime"))
        if one("entityType"):
            kw["entity_type"] = one("entityType")
        if one("entityId"):
            kw["entity_id"] = one("entityId")
        if params.get("event"):
            kw["event_names"] = params["event"]
        tet, tei = one("targetEntityType"), one("targetEntityId")
        if tet:
            kw["target_entity_type"] = NO_TARGET if tet == "none" else tet
        if tei:
            kw["target_entity_id"] = NO_TARGET if tei == "none" else tei
        if one("limit"):
            kw["limit"] = int(one("limit"))
        if one("reversed"):
            kw["reversed"] = one("reversed").lower() == "true"
        return kw

    # -- http ---------------------------------------------------------------
    def _make_handler(server: "EventServer"):
        class Handler(JsonRequestHandler):
            server_logger = logger

            def _params(self) -> dict[str, list[str]]:
                q = urllib.parse.urlparse(self.path).query
                return urllib.parse.parse_qs(q)

            def _route(self) -> str:
                return urllib.parse.urlparse(self.path).path

            def _auth(self):
                return server.authenticate(self._params())

            def _book(self, app_id: int, status: int, event=None):
                if server.stats is not None:
                    server.stats.bookkeeping(app_id, status, event)

            def _reply_503(self, e: BaseException):
                """Storage still unavailable after retries: tell the
                client when to come back instead of failing opaquely."""
                self.extra_headers = [("Retry-After", "1")]
                self._reply(503, {
                    "message": f"event store unavailable: {e}",
                    "error": "StorageUnavailable",
                })

            def _reply_503_shard(self, e: ShardUnavailableError):
                """One shard is down, the fleet is not: a structured
                503 naming the shard, with a Retry-After sized for a
                worker respawn rather than a lock blip.  Clients
                (loadgen, bench) book this as throttled-and-retry, not
                as an error."""
                INGEST_SHARD_UNAVAILABLE_TOTAL.labels(
                    shard=str(e.shard)
                ).inc()
                self.extra_headers = [("Retry-After", "2")]
                self._reply(503, {
                    "message": str(e),
                    "error": "ShardUnavailable",
                    "shard": e.shard,
                })

            # ---- POST ----
            def do_POST(self):
                path = self._route()
                # propagate (never mint) the trace id: ingestion is a
                # downstream hop — ids are born at the serving edge or
                # the client
                with trace_scope(self._trace_id()):
                    self._do_post(path)

            def _do_post(self, path):
                try:
                    if path == "/events.json":
                        self._post_event()
                    elif path == "/batch/events.json":
                        self._post_batch()
                    elif path.startswith("/webhooks/"):
                        self._post_webhook(path)
                    else:
                        self._reply(404, {"message": "not found"})
                except AuthError as e:
                    self._reply(401, {"message": str(e)})
                except (EventValidationError, ConnectorError,
                        json.JSONDecodeError, ValueError) as e:
                    self._reply(400, {"message": str(e)})
                except ShardUnavailableError as e:
                    self._reply_503_shard(e)
                except TRANSIENT_STORAGE_ERRORS as e:
                    self._reply_503(e)
                except Exception as e:
                    logger.exception("event server error")
                    self._reply(500, {"message": str(e)})

            def _post_event(self):
                # pulse ingest timeline (auth/parse/store_write/reply):
                # the tail of ingestion latency decomposes the same way
                # serving queries do.  Only the 201 path observes —
                # rejected requests have no meaningful decomposition.
                tl = timeline.Timeline("events")
                app_id, channel_id, allowed = self._auth()
                tl.mark("auth")
                try:
                    event = Event.from_json(json.loads(self._body().decode()))
                except (EventValidationError, json.JSONDecodeError,
                        ValueError) as e:
                    self._book(app_id, 400)
                    self._reply(400, {"message": str(e)})
                    return
                tl.mark("parse")
                try:
                    eid = server.insert_event(event, app_id, channel_id, allowed)
                except AuthError as e:
                    self._book(app_id, 401)
                    self._reply(401, {"message": str(e)})
                    return
                except ShardUnavailableError as e:
                    self._book(app_id, 503)
                    self._reply_503_shard(e)
                    return
                except TRANSIENT_STORAGE_ERRORS as e:
                    self._book(app_id, 503)
                    self._reply_503(e)
                    return
                tl.mark("store_write")
                self._book(app_id, 201, event)
                self._reply(201, {"eventId": eid})
                tl.mark("reply")
                tl.finish()

            def _post_batch(self):
                """Batch insert: per-event status
                (reference EventAPI batch route)."""
                app_id, channel_id, allowed = self._auth()
                # whole-body rejections are still this app's traffic:
                # book the 400 or /stats.json under-counts rejections
                try:
                    items = json.loads(self._body().decode())
                    if not isinstance(items, list):
                        raise ValueError("batch body must be a JSON array")
                    if len(items) > 50:
                        # the reference's limit (EventAPI.scala batch
                        # route); the REST path is for live trickle
                        # ingest — bulk loads belong on `pio-tpu import`
                        # (native scanner, one transaction, 55-95k
                        # events/s)
                        raise ValueError(
                            "batch limited to 50 events; use `pio-tpu "
                            "import` for bulk loads"
                        )
                except (json.JSONDecodeError, ValueError):
                    self._book(app_id, 400)
                    raise
                es = server.storage.get_event_store()
                es.init_channel(app_id, channel_id)
                # Parse/validate first, then insert every valid event in
                # ONE insert_batch (one executemany + one WAL commit):
                # per-event inserts put this route at 7.3k ev/s vs 33k
                # for the importer (SERVING_BENCH.md).  Statuses stay
                # positional; invalid events don't block valid siblings;
                # duplicate eventIds keep last-in-batch-wins order
                # (executemany preserves row order).  from_json already
                # validates, so validate=False skips the second pass —
                # same contract the bulk importer relies on.
                results: list[Optional[dict]] = [None] * len(items)
                valid: list[tuple[int, Event]] = []
                for k, item in enumerate(items):
                    try:
                        event = Event.from_json(item)
                        server.check_allowed(event, allowed)
                        valid.append((k, event))
                    except AuthError as e:
                        self._book(app_id, 401)
                        results[k] = {"status": 401, "message": str(e)}
                    except (EventValidationError, ValueError) as e:
                        self._book(app_id, 400)
                        results[k] = {"status": 400, "message": str(e)}
                if server.wal is not None:
                    server._seen_channels.add((app_id, channel_id))
                    fresh = iter(new_event_ids(len(valid)))
                    vids = [e.event_id or next(fresh) for _, e in valid]

                    def put_batch():
                        faults.check("storage.write")
                        server.wal.submit(
                            app_id, channel_id,
                            [event_to_row(e, eid)
                             for (_, e), eid in zip(valid, vids)],
                        )
                        return vids
                else:
                    def put_batch():
                        faults.check("storage.write")
                        return es.insert_batch(
                            [e for _, e in valid], app_id, channel_id,
                            validate=False,
                        )

                def timed_put_batch():
                    t0 = time.perf_counter()
                    try:
                        return server.write_retry.call(
                            put_batch, retry_on=TRANSIENT_STORAGE_ERRORS,
                            on_retry=server._note_retry("storage.write"),
                        )
                    finally:
                        dt = time.perf_counter() - t0
                        EVENT_WRITE_LATENCY.child().observe(dt)
                        get_tracer().record(
                            "events.write", dt, attrs={"n": len(valid)}
                        )

                try:
                    ids = timed_put_batch() if valid else []
                except ShardUnavailableError:
                    # one shard refused the whole-batch submit (which
                    # guards every row before logging any, so nothing
                    # was acknowledged).  Fall back to per-shard
                    # groups: healthy shards accept, only the dead
                    # shard's events answer 503 — the one-shard-down
                    # contract at batch granularity.
                    self._post_batch_degraded(app_id, channel_id,
                                              valid, results)
                    return
                except TRANSIENT_STORAGE_ERRORS as e:
                    # the batch contract is per-event statuses even when
                    # the store is down: valid events answer 503 (come
                    # back), invalid siblings keep their 400/401
                    for k, _ in valid:
                        self._book(app_id, 503)
                        results[k] = {
                            "status": 503,
                            "message": f"event store unavailable: {e}",
                        }
                    self.extra_headers = [("Retry-After", "1")]
                    self._reply(200, results)
                    return
                for (k, event), eid in zip(valid, ids):
                    self._book(app_id, 201, event)
                    results[k] = {"status": 201, "eventId": eid}
                self._reply(200, results)

            def _post_batch_degraded(self, app_id, channel_id, valid,
                                     results):
                """Shard-isolated batch retry: submit per shard group
                so a dead shard only fails ITS events.  Per-shard
                all-or-nothing is preserved (each submit guards every
                row first)."""
                wal = server.wal
                groups: dict[int, list[tuple[int, Event]]] = {}
                for k, e in valid:
                    six = wal.route(e.entity_type, e.entity_id)
                    groups.setdefault(six, []).append((k, e))
                down: list[int] = []
                for six, group in sorted(groups.items()):
                    fresh = iter(new_event_ids(len(group)))
                    gids = [e.event_id or next(fresh) for _, e in group]
                    try:
                        wal.submit(
                            app_id, channel_id,
                            [event_to_row(e, eid)
                             for (_, e), eid in zip(group, gids)],
                        )
                    except ShardUnavailableError as e2:
                        down.append(six)
                        INGEST_SHARD_UNAVAILABLE_TOTAL.labels(
                            shard=str(six)
                        ).inc(len(group))
                        for k, _ in group:
                            self._book(app_id, 503)
                            results[k] = {
                                "status": 503,
                                "message": str(e2),
                                "error": "ShardUnavailable",
                                "shard": six,
                            }
                        continue
                    for (k, event), eid in zip(group, gids):
                        self._book(app_id, 201, event)
                        results[k] = {"status": 201, "eventId": eid}
                if down:
                    self.extra_headers = [("Retry-After", "2")]
                self._reply(200, results)

            def _post_webhook(self, path: str):
                app_id, channel_id, allowed = self._auth()
                name = path[len("/webhooks/"):]
                if name.endswith(".json"):
                    connector = JSON_CONNECTORS.get(name[: -len(".json")])
                    if connector is None:
                        self._reply(404, {"message": f"webhook {name} not found"})
                        return
                    data = json.loads(self._body().decode() or "{}")
                elif name.endswith(".form"):
                    connector = FORM_CONNECTORS.get(name[: -len(".form")])
                    if connector is None:
                        self._reply(404, {"message": f"webhook {name} not found"})
                        return
                    form = urllib.parse.parse_qs(
                        self._body().decode(), keep_blank_values=True
                    )
                    data = {k: v[0] for k, v in form.items()}
                else:
                    self._reply(404, {"message": "unknown webhook format"})
                    return
                event = to_event(connector, data)
                try:
                    eid = server.insert_event(
                        event, app_id, channel_id, allowed
                    )
                except TRANSIENT_STORAGE_ERRORS:
                    self._book(app_id, 503)
                    raise  # central handler answers 503 + Retry-After
                self._book(app_id, 201, event)
                self._reply(201, {"eventId": eid})

            # ---- GET ----
            def do_GET(self):
                if self._serve_metrics():
                    return
                path = self._route()
                try:
                    if path == "/":
                        self._reply(200, {
                            "status": "alive",
                            "description": "predictionio_tpu event server",
                        })
                    elif path == "/events.json":
                        self._get_events()
                    elif path.startswith("/events/") and path.endswith(".json"):
                        self._get_event(path[len("/events/"):-len(".json")])
                    elif path == "/stats.json":
                        self._get_stats()
                    elif path.startswith("/webhooks/"):
                        name = path[len("/webhooks/"):]
                        base = name.rsplit(".", 1)[0]
                        if base in JSON_CONNECTORS or base in FORM_CONNECTORS:
                            self._auth()
                            self._reply(200, {"message": f"webhook {base} connected"})
                        else:
                            self._reply(404, {"message": f"webhook {name} not found"})
                    else:
                        self._reply(404, {"message": "not found"})
                except AuthError as e:
                    self._reply(401, {"message": str(e)})
                except ValueError as e:
                    self._reply(400, {"message": str(e)})
                except ShardUnavailableError as e:
                    self._reply_503_shard(e)
                except TRANSIENT_STORAGE_ERRORS as e:
                    self._reply_503(e)
                except Exception as e:
                    logger.exception("event server error")
                    self._reply(500, {"message": str(e)})

            def _scan(self, app_id, fn):
                """Run a storage read through the injection point and
                the transient-error retry policy."""
                def read():
                    faults.check("storage.read")
                    # read-your-writes under the WAL: a 201 means
                    # "fsynced", not "committed" — drain before scanning
                    # so this server's own GETs see their POSTs
                    server.barrier()
                    return fn()

                try:
                    return server.write_retry.call(
                        read, retry_on=TRANSIENT_STORAGE_ERRORS,
                        on_retry=server._note_retry("storage.read"),
                    )
                except TRANSIENT_STORAGE_ERRORS:
                    self._book(app_id, 503)
                    raise

            def _get_events(self):
                app_id, channel_id, _ = self._auth()
                kw = server._find_kwargs(self._params())
                es = server.storage.get_event_store()
                es.init_channel(app_id, channel_id)
                events = self._scan(app_id, lambda: list(
                    es.find(app_id=app_id, channel_id=channel_id, **kw)
                ))
                self._book(app_id, 200)
                if not events:
                    self._reply(404, {"message": "Not Found"})
                else:
                    self._reply(200, [e.to_json() for e in events])

            def _get_event(self, event_id: str):
                app_id, channel_id, _ = self._auth()
                es = server.storage.get_event_store()
                es.init_channel(app_id, channel_id)
                e = self._scan(
                    app_id, lambda: es.get(event_id, app_id, channel_id)
                )
                if e is None:
                    self._reply(404, {"message": "Not Found"})
                else:
                    self._reply(200, e.to_json())

            def _get_stats(self):
                app_id, _, _ = self._auth()
                if server.stats is None:
                    self._reply(404, {"message": "stats disabled"})
                else:
                    self._reply(200, server.stats.to_json(app_id))

            # ---- DELETE ----
            def do_DELETE(self):
                path = self._route()
                try:
                    if path.startswith("/events/") and path.endswith(".json"):
                        app_id, channel_id, _ = self._auth()
                        eid = path[len("/events/"):-len(".json")]
                        es = server.storage.get_event_store()
                        es.init_channel(app_id, channel_id)
                        # a delete must see (and remove) the caller's
                        # own just-acknowledged writes
                        server.barrier()
                        if es.delete(eid, app_id, channel_id):
                            self._reply(200, {"message": "Found"})
                        else:
                            self._reply(404, {"message": "Not Found"})
                    else:
                        self._reply(404, {"message": "not found"})
                except AuthError as e:
                    self._reply(401, {"message": str(e)})
                except ShardUnavailableError as e:
                    self._reply_503_shard(e)
                except TRANSIENT_STORAGE_ERRORS as e:
                    self._reply_503(e)
                except Exception as e:
                    logger.exception("event server error")
                    self._reply(500, {"message": str(e)})

        return Handler
