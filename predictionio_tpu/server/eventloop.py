"""pio-surge: selector-based event-loop HTTP edge.

The stdlib ``ThreadingHTTPServer`` edge spends one OS thread per
*connection*: at c16 keep-alive load the pulse sweep measured p99
blowing out to ~65 ms from thread churn + condvar wakeups + the
``BaseHTTPRequestHandler`` readline/email parse per request, while
``queue_wait``/``batch_wait`` dominated the timeline.  This module is
the replacement front end: ONE loop thread multiplexes every
connection through a ``selectors.DefaultSelector`` — it accepts,
parses, enforces the connection cap, and hands complete requests to a
handler that must *never block the loop* (device work rides the
micro-batcher's dispatcher thread, blocking routes ride a small aux
pool; piolint rule PIO110 guards the discipline via
:func:`callback_scope`).

Responses may complete on any thread: :class:`Responder` is handed to
the handler and is safe to call exactly once from wherever the work
finished — off-loop completions enqueue the rendered bytes and wake
the selector through a self-pipe.

Interface parity: the class exposes the ``server_address`` /
``serve_forever`` / ``shutdown`` / ``server_close`` surface of
``socketserver.BaseServer`` so ``HTTPServerBase`` drives either edge
through one lifecycle (bind-in-caller, ephemeral-port re-read,
EADDRINUSE retry, stop-handshake semantics all unchanged).

Deliberate non-features: no chunked transfer encoding (every client in
this system sends Content-Length), no TLS, no HTTP/2 — a reverse proxy
owns those concerns in production; this edge owns the query hot path.
"""

from __future__ import annotations

import json
import logging
import selectors
import socket
import threading
import time
from typing import Callable, Optional

from ..obs import HTTP_CONN_REJECTED, HTTP_OPEN_CONNECTIONS

__all__ = [
    "EventLoopHTTPServer",
    "Request",
    "Responder",
    "callback_scope",
    "DEFAULT_MAX_CONNECTIONS",
]

logger = logging.getLogger(__name__)

DEFAULT_MAX_CONNECTIONS = 512
# a request head (request line + headers) larger than this is a client
# error or an attack; bounding it is half the slow-loris guard (the
# connection cap is the other half)
MAX_HEADER_BYTES = 32 * 1024
MAX_BODY_BYTES = 16 * 1024 * 1024
# keep-alive connections idle longer than this are closed on the next
# sweep so a silent client can't hold a cap slot forever
IDLE_TIMEOUT_S = 120.0


def callback_scope(fn):
    """Marker decorator for functions that run ON the event-loop
    thread (request handlers and completion callbacks).  Identity at
    runtime; piolint rule PIO110 flags blocking calls — ``time.sleep``,
    blocking socket I/O, ``queue.Queue.get()`` without a timeout —
    inside any function carrying this decorator (or any ``async def``),
    because one blocked callback stalls EVERY connection."""
    return fn


class Request:
    """One parsed HTTP request (headers lower-cased, body complete)."""

    __slots__ = ("method", "path", "headers", "body")

    def __init__(self, method: str, path: str, headers: dict, body: bytes):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body

    def header(self, name: str, default: Optional[str] = None):
        return self.headers.get(name.lower(), default)


class Responder:
    """One-shot response channel for a single request.

    ``respond()`` is thread-safe and idempotent-hostile: the second
    call raises — a handler that answered twice has a logic bug worth
    surfacing.  ``tl`` (a pulse Timeline) is optional; when given, the
    loop marks the ``write`` segment and finishes the timeline after
    the response bytes reach the socket, so the accounting identity
    (segments sum to covered wall time) holds across the async edge.
    """

    __slots__ = ("_server", "_conn", "_done", "_lock")

    def __init__(self, server: "EventLoopHTTPServer", conn: "_Conn"):
        self._server = server
        self._conn = conn
        self._done = False
        self._lock = threading.Lock()

    def __call__(self, code: int, payload,
                 ctype: str = "application/json",
                 extra_headers=(), tl=None, close: bool = False) -> None:
        with self._lock:
            if self._done:
                raise RuntimeError("request already answered")
            self._done = True
        body = (
            payload if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        data = self._server._render(code, body, ctype, extra_headers, close)
        self._server._complete(self._conn, data, tl, close)


_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 409: "Conflict", 411: "Length Required",
    431: "Request Header Fields Too Large", 500: "Internal Server Error",
    503: "Service Unavailable",
}


class _Conn:
    """Per-connection state: read buffer, parse state, write queue."""

    __slots__ = ("sock", "addr", "rbuf", "wbuf", "woff", "busy",
                 "closing", "tl", "last_activity", "need", "registered")

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.rbuf = bytearray()
        self.wbuf: list[bytes] = []
        self.woff = 0          # offset into wbuf[0]
        self.busy = False      # a request is in flight (handler owns it)
        self.closing = False   # close once wbuf drains
        self.tl = None         # pulse timeline to finish after the write
        self.last_activity = time.monotonic()
        self.need = None       # (request head, content-length) mid-body
        self.registered = selectors.EVENT_READ


class EventLoopHTTPServer:
    """One selector loop serving many connections; see module doc."""

    def __init__(self, server_address, handler:
                 Callable[[Request, Responder], None],
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 name: str = "serving",
                 idle_timeout_s: float = IDLE_TIMEOUT_S):
        self.handler = handler
        self.name = name
        self.max_connections = max_connections
        self.idle_timeout_s = idle_timeout_s
        self._sel = selectors.DefaultSelector()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._lsock.bind(server_address)
            self._lsock.listen(min(max_connections, socket.SOMAXCONN))
        except BaseException:
            self._lsock.close()
            raise
        self._lsock.setblocking(False)
        self.server_address = self._lsock.getsockname()
        # self-pipe: off-loop completions + shutdown wake the selector
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._conns: set[_Conn] = set()
        self._pending_lock = threading.Lock()
        self._pending: list[tuple[_Conn, bytes, object, bool]] = []
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._m_open = HTTP_OPEN_CONNECTIONS.labels(server=name)
        self._m_rejected = HTTP_CONN_REJECTED.labels(server=name)

    # -- BaseServer-compatible lifecycle -----------------------------------
    def serve_forever(self) -> None:
        from ..obs import scope

        # pio-scope: the loop thread is THE suspect at router
        # saturation — its running-share on /debug/pprof is the
        # single-core ceiling evidence
        scope.register_thread_role("eventloop")
        self._loop_thread = threading.current_thread()
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        try:
            while not self._stop.is_set():
                events = self._sel.select(timeout=1.0)
                for key, mask in events:
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        self._drain_wakeups()
                    else:
                        conn = key.data
                        if mask & selectors.EVENT_READ:
                            self._readable(conn)
                        if mask & selectors.EVENT_WRITE:
                            self._writable(conn)
                now = time.monotonic()
                if now - last_sweep >= 5.0:
                    last_sweep = now
                    self._sweep_idle(now)
        finally:
            self._stopped.set()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake()
        self._stopped.wait(10.0)

    def server_close(self) -> None:
        for conn in list(self._conns):
            self._close_conn(conn)
        for s in (self._lsock, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except Exception:
            pass

    # -- loop internals ----------------------------------------------------
    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _drain_wakeups(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass
        with self._pending_lock:
            pending, self._pending = self._pending, []
        for conn, data, tl, close in pending:
            if conn in self._conns:
                conn.tl = tl
                conn.closing = conn.closing or close
                conn.wbuf.append(data)
                self._writable(conn)

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            if len(self._conns) >= self.max_connections:
                # the structured overflow answer: a bounded edge sheds
                # load visibly instead of queueing sockets to die
                self._m_rejected.inc()
                self._refuse(sock)
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, addr)
            self._conns.add(conn)
            self._m_open.set(float(len(self._conns)))
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _refuse(self, sock: socket.socket) -> None:
        body = json.dumps({
            "message": "connection limit reached",
            "error": "TooManyConnections",
        }).encode()
        try:
            sock.setblocking(False)
            sock.send(
                b"HTTP/1.1 503 Service Unavailable\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Retry-After: 1\r\nConnection: close\r\n\r\n" + body
            )
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _sweep_idle(self, now: float) -> None:
        for conn in [c for c in self._conns
                     if not c.busy and not c.wbuf
                     and now - c.last_activity > self.idle_timeout_s]:
            self._close_conn(conn)

    def _close_conn(self, conn: _Conn) -> None:
        if conn not in self._conns:
            return
        self._conns.discard(conn)
        self._m_open.set(float(len(self._conns)))
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _set_interest(self, conn: _Conn, events: int) -> None:
        if conn.registered == events or conn not in self._conns:
            return
        conn.registered = events
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _readable(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not chunk:
            # peer closed; any in-flight response has nowhere to go
            self._close_conn(conn)
            return
        conn.last_activity = time.monotonic()
        conn.rbuf += chunk
        if len(conn.rbuf) > MAX_HEADER_BYTES and conn.need is None \
                and b"\r\n\r\n" not in conn.rbuf:
            self._error_close(conn, 431, "request head too large")
            return
        self._try_dispatch(conn)

    def _try_dispatch(self, conn: _Conn) -> None:
        """Parse + hand off at most ONE request; further pipelined
        bytes wait in rbuf until the response is written (responses
        must go out in request order on a connection)."""
        if conn.busy or conn.closing:
            return
        if conn.need is None:
            end = conn.rbuf.find(b"\r\n\r\n")
            if end < 0:
                return
            head = bytes(conn.rbuf[:end])
            del conn.rbuf[:end + 4]
            try:
                req = self._parse_head(head)
            except ValueError as e:
                self._error_close(conn, 400, f"bad request: {e}")
                return
            if req.header("transfer-encoding"):
                self._error_close(
                    conn, 411, "chunked transfer encoding not supported"
                )
                return
            try:
                length = int(req.header("content-length", "0") or "0")
            except ValueError:
                self._error_close(conn, 400, "bad Content-Length")
                return
            if length < 0 or length > MAX_BODY_BYTES:
                self._error_close(conn, 400, "unacceptable Content-Length")
                return
            conn.need = (req, length)
        req, length = conn.need
        if len(conn.rbuf) < length:
            return
        body = bytes(conn.rbuf[:length])
        del conn.rbuf[:length]
        conn.need = None
        req.body = body
        if req.header("connection", "").lower() == "close":
            conn.closing = True
        conn.busy = True
        responder = Responder(self, conn)
        try:
            self.handler(req, responder)
        except Exception as e:  # a crashed handler must still answer
            logger.exception("event-loop handler failed")
            try:
                responder(500, {"message": f"internal error: {e}"})
            except RuntimeError:
                pass  # handler answered before raising

    @staticmethod
    def _parse_head(head: bytes) -> Request:
        try:
            text = head.decode("iso-8859-1")
        except UnicodeDecodeError as e:
            raise ValueError(str(e)) from None
        lines = text.split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
            raise ValueError(f"malformed request line: {lines[0]!r}")
        method, path, _version = parts
        headers: dict[str, str] = {}
        for ln in lines[1:]:
            if not ln:
                continue
            k, sep, v = ln.partition(":")
            if not sep:
                raise ValueError(f"malformed header line: {ln!r}")
            headers[k.strip().lower()] = v.strip()
        return Request(method, path, headers, b"")

    def _error_close(self, conn: _Conn, code: int, message: str) -> None:
        data = self._render(code, json.dumps({"message": message}).encode(),
                            "application/json", (), close=True)
        conn.closing = True
        conn.wbuf.append(data)
        self._writable(conn)

    def _render(self, code: int, body: bytes, ctype: str,
                extra_headers, close: bool) -> bytes:
        reason = _REASONS.get(code, "Unknown")
        out = [
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
        ]
        for k, v in extra_headers:
            out.append(f"{k}: {v}\r\n")
        if close:
            out.append("Connection: close\r\n")
        out.append("\r\n")
        return "".join(out).encode("iso-8859-1") + body

    def _complete(self, conn: _Conn, data: bytes, tl, close: bool) -> None:
        """Queue a rendered response; thread-safe (a Responder may fire
        from the batcher dispatcher or the aux pool)."""
        if threading.current_thread() is self._loop_thread:
            if conn in self._conns:
                conn.tl = tl
                conn.closing = conn.closing or close
                conn.wbuf.append(data)
                self._writable(conn)
            return
        with self._pending_lock:
            self._pending.append((conn, data, tl, close))
        self._wake()

    def _writable(self, conn: _Conn) -> None:
        try:
            while conn.wbuf:
                buf = conn.wbuf[0]
                n = conn.sock.send(
                    memoryview(buf)[conn.woff:] if conn.woff else buf
                )
                conn.woff += n
                if conn.woff < len(buf):
                    break
                conn.wbuf.pop(0)
                conn.woff = 0
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close_conn(conn)
            return
        if conn.wbuf:
            self._set_interest(
                conn, selectors.EVENT_READ | selectors.EVENT_WRITE
            )
            return
        # response fully flushed: close the request's timeline (the
        # write segment ends at the last successful send) and either
        # close the connection or look for the next pipelined request
        self._set_interest(conn, selectors.EVENT_READ)
        if conn.tl is not None:
            tl, conn.tl = conn.tl, None
            tl.mark("write")
            tl.finish()
        if conn.busy:
            conn.busy = False
            conn.last_activity = time.monotonic()
        if conn.closing:
            self._close_conn(conn)
        elif conn.rbuf:
            self._try_dispatch(conn)
