"""pio-forge engine specs: the one-file-engine registry.

The reference PredictionIO's lasting value was its template ecosystem —
``DataSource -> Preparator -> Algorithm -> Serving`` made a *new engine*
cheap and the surrounding server did the rest (``pio train/deploy/eval``
over pluggable engines).  :class:`EngineSpec` is that contract made
explicit: ONE declaration per engine (factory + params schema + query
example + conformance fixture), registered by decorator, and every
platform surface lights up from registration alone:

* ``pio-tpu engines list/describe`` and ``train/deploy/eval/foldin
  --engine NAME`` dispatch (`cli/main.py`);
* ``pio-tpu template list/get`` gallery entries
  (`tools/template_gallery.py` derives its gallery from this registry);
* pio-tower run manifests and the ``pio_engine_queries_total{engine=}``
  obs labels (`workflow/train.py`, `server/serving.py`);
* pio-hive tenant manifests (a ``tenants.json`` entry may name any
  registered engine instead of an engine.json path);
* the registry-parametrized conformance suite
  (`tests/test_engine_conformance.py`) — every registered engine is
  driven train -> deploy -> query -> feedback -> eval plus a chaos and
  an obs assertion, so a new engine inherits the serving/obs/chaos
  infrastructure by construction.

Registration is side-effect-of-import: decorating a zero-arg factory
registers the spec, and :func:`~predictionio_tpu.engines.discovery.
discover` imports the built-in ``templates/`` package plus any user
engine dirs on ``PIO_TPU_ENGINE_PATH``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional, Sequence

__all__ = [
    "ConformanceFixture",
    "EngineSpec",
    "engine_spec",
    "register",
    "get_engine_spec",
    "list_engine_specs",
    "spec_name_of",
    "clear_registry",
]


@dataclass(frozen=True)
class ConformanceFixture:
    """Everything the conformance suite needs to drive an engine end to
    end with NO engine-specific test code: events to seed, a tiny-train
    variant, queries to fire, and a predicate over the result JSON.

    ``seed_events`` is a zero-arg callable (not a literal list) so event
    times can be minted at run time — the trending engine's decay math
    needs *recent* timestamps, not scaffold-time constants.
    """

    app_name: str
    seed_events: Callable[[], Sequence[Any]]
    queries: tuple[dict, ...]
    check: Optional[Callable[[Any], bool]] = None
    # tiny-train variant override; None = the spec's default_params
    # (conformance must stay seconds-per-engine, so specs whose gallery
    # defaults train 20 ALS sweeps pass a rank-4 / 3-sweep variant here)
    variant: Optional[Mapping[str, Any]] = None


@dataclass(frozen=True)
class EngineSpec:
    """One engine, declared once.

    ``factory`` is the zero-arg callable producing the
    :class:`~predictionio_tpu.controller.engine.Engine`;
    ``default_params`` is the engine.json-shaped component params dict
    (``datasource``/``preparator``/``algorithms``/``serving`` keys) that
    seeds both the template gallery scaffold and ``--engine NAME``
    dispatch when no engine.json exists."""

    name: str
    description: str
    factory: Callable[[], Any]
    factory_path: str
    default_params: Mapping[str, Any] = field(default_factory=dict)
    query_example: Mapping[str, Any] = field(default_factory=dict)
    # optional zero-arg callable returning a controller Evaluation —
    # `pio-tpu eval --engine NAME` dispatches through it
    evaluation: Optional[Callable[[], Any]] = None
    evaluation_path: Optional[str] = None
    conformance: Optional[ConformanceFixture] = None
    source: str = "builtin"

    # -- dispatch ---------------------------------------------------------
    def build(self):
        """Factory call; the instance is stamped with the spec name so
        every downstream surface (serving labels, tower manifests) can
        recover it without threading one more argument around."""
        engine = self.factory()
        engine._engine_spec_name = self.name
        return engine

    def default_variant(self) -> dict:
        """The synthetic engine.json for registry dispatch: what
        ``--engine NAME`` trains/serves when no engine.json file
        exists.  ``engine`` (not ``engineFactory``) is the loader key;
        ``engine:<name>`` is the engine-variant string instances are
        registered under (`instance_variant_key`)."""
        return {
            "id": self.name,
            "engine": self.name,
            "description": self.description,
            **{k: _plain(v) for k, v in self.default_params.items()},
        }

    def instance_variant_key(self) -> str:
        return f"engine:{self.name}"

    def describe(self) -> dict:
        return {
            "name": self.name,
            "description": self.description,
            "factory": self.factory_path,
            "source": self.source,
            "defaultParams": _plain(self.default_params),
            "queryExample": _plain(self.query_example),
            "evaluation": self.evaluation_path,
            "conformance": self.conformance is not None,
        }


def _plain(v):
    """Deep-copy mappings/sequences to plain json-shaped types."""
    if isinstance(v, Mapping):
        return {k: _plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    return v


_lock = threading.Lock()
_registry: dict[str, EngineSpec] = {}
# set by discovery while importing a user engine dir so decorators in
# that module register with the right provenance
_current_source: str = "builtin"


def register(spec: EngineSpec) -> EngineSpec:
    """Idempotent per (name, factory_path); a DIFFERENT factory under an
    existing name is a collision and refuses loudly — silently shadowing
    a built-in engine would make `--engine NAME` ambiguous."""
    with _lock:
        prior = _registry.get(spec.name)
        if prior is not None and prior.factory_path != spec.factory_path:
            raise ValueError(
                f"engine {spec.name!r} is already registered by "
                f"{prior.factory_path} (source: {prior.source}); "
                f"refusing to overwrite with {spec.factory_path}"
            )
        _registry[spec.name] = spec
    return spec


def engine_spec(
    name: str,
    *,
    description: str = "",
    default_params: Optional[Mapping[str, Any]] = None,
    query_example: Optional[Mapping[str, Any]] = None,
    evaluation: Optional[Callable[[], Any]] = None,
    conformance: Optional[ConformanceFixture] = None,
):
    """Decorator: register a zero-arg engine factory as an engine.

    The decorated function keeps working as a plain factory (examples
    and tests call it directly); engines it returns are stamped with the
    spec name either way."""

    def wrap(factory: Callable[[], Any]):
        import functools

        @functools.wraps(factory)
        def stamped():
            engine = factory()
            engine._engine_spec_name = name
            return engine

        desc = description
        if not desc and factory.__doc__:
            desc = factory.__doc__.strip().splitlines()[0]
        spec = EngineSpec(
            name=name,
            description=desc,
            factory=stamped,
            factory_path=f"{factory.__module__}.{factory.__qualname__}",
            default_params=dict(default_params or {}),
            query_example=dict(query_example or {}),
            evaluation=evaluation,
            evaluation_path=(
                f"{evaluation.__module__}.{evaluation.__qualname__}"
                if evaluation is not None else None
            ),
            conformance=conformance,
            source=_current_source,
        )
        register(spec)
        stamped.__engine_spec__ = spec
        return stamped

    return wrap


def get_engine_spec(name: str) -> EngineSpec:
    from .discovery import discover

    discover()
    with _lock:
        spec = _registry.get(name)
        if spec is None:
            known = ", ".join(sorted(_registry)) or "(none)"
            raise KeyError(
                f"no engine named {name!r} is registered; known: {known}"
                " — set PIO_TPU_ENGINE_PATH to add user engine dirs"
            )
        return spec


def list_engine_specs() -> list[EngineSpec]:
    from .discovery import discover

    discover()
    with _lock:
        return sorted(_registry.values(), key=lambda s: s.name)


def spec_name_of(obj: Any) -> Optional[str]:
    """The registered engine name of an Engine instance (or a factory),
    or None for engines built outside the registry."""
    name = getattr(obj, "_engine_spec_name", None)
    if name is not None:
        return name
    spec = getattr(obj, "__engine_spec__", None)
    return spec.name if spec is not None else None


def clear_registry(keep_builtin: bool = True) -> None:
    """Test hook: drop user-dir registrations (or everything)."""
    with _lock:
        if keep_builtin:
            for k in [k for k, s in _registry.items()
                      if s.source != "builtin"]:
                del _registry[k]
        else:
            _registry.clear()
