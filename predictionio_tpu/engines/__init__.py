"""pio-forge: the engine platform — a new engine is ONE file.

``spec.py`` holds the :class:`EngineSpec` registry (declare + register
by decorator), ``discovery.py`` finds engines (built-in ``templates/``
package + user dirs on ``PIO_TPU_ENGINE_PATH``), and ``resolve()`` is
the dispatch point the CLI / tenancy / conformance surfaces share.
"""

from __future__ import annotations

from typing import Any, Optional

from .discovery import ENGINE_PATH_ENV, discover
from .spec import (
    ConformanceFixture,
    EngineSpec,
    clear_registry,
    engine_spec,
    get_engine_spec,
    list_engine_specs,
    register,
    spec_name_of,
)

__all__ = [
    "ConformanceFixture",
    "EngineSpec",
    "ENGINE_PATH_ENV",
    "clear_registry",
    "discover",
    "engine_label_of",
    "engine_spec",
    "get_engine_spec",
    "list_engine_specs",
    "register",
    "resolve",
    "spec_name_of",
]


def resolve(name: str, variant_overrides: Optional[dict] = None):
    """Registry dispatch: ``(engine, engine_params, variant)`` for a
    registered engine name — the no-engine.json analogue of
    ``cli.main.load_engine_from_variant``.  ``variant_overrides``
    replace same-named component keys of the spec's default variant
    (an engine.json that says ``{"engine": "trending", "algorithms":
    [...]}`` keeps the spec's datasource defaults but its own algorithm
    params)."""
    spec = get_engine_spec(name)
    variant = spec.default_variant()
    if variant_overrides:
        variant.update({k: v for k, v in variant_overrides.items()
                        if v is not None})
    engine = spec.build()
    return engine, engine.params_from_variant(variant), variant


def engine_label_of(engine: Any, fallback: str = "custom") -> str:
    """The obs/tower label for an engine instance: its registered spec
    name, else ``fallback`` (unregistered engines stay observable, just
    under a generic label)."""
    return spec_name_of(engine) or fallback
