"""Engine discovery: built-in templates + ``PIO_TPU_ENGINE_PATH`` dirs.

Two sources, one registry:

* the built-in ``predictionio_tpu.templates`` package — every
  non-underscore module is imported, and each module's
  ``@engine_spec(...)`` decorators register on import;
* user engine dirs named by ``PIO_TPU_ENGINE_PATH`` (``os.pathsep``
  separated).  Each dir holds an ``engine.json`` pointing at a module —
  ``engineModule`` (a module name resolved inside the dir, default
  ``engine``) or ``engineFactory`` (dotted path whose top segment is the
  module file).  The dir goes on ``sys.path``, the module is imported,
  and its decorators register with ``source=<dir>`` — a from-scratch
  engine is ONE ``engine.py`` plus a two-line ``engine.json``
  (`tools/forge_smoke.py` proves that flow in the gate).

Discovery is lazy and idempotent: the first registry read triggers it;
``discover(refresh=True)`` re-walks the env var (tests and long-lived
servers whose operator appends a dir).
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import pkgutil
import sys
import threading
from pathlib import Path

from . import spec as _spec

logger = logging.getLogger(__name__)

__all__ = ["discover", "load_engine_dir", "ENGINE_PATH_ENV"]

ENGINE_PATH_ENV = "PIO_TPU_ENGINE_PATH"

_lock = threading.Lock()
_done = False
_loaded_dirs: set[str] = set()


def discover(refresh: bool = False) -> None:
    global _done
    with _lock:
        if _done and not refresh:
            return
        _import_builtin_templates()
        for raw in os.environ.get(ENGINE_PATH_ENV, "").split(os.pathsep):
            raw = raw.strip()
            if raw:
                _load_user_dir(Path(raw))
        _done = True


def load_engine_dir(engine_dir) -> None:
    """Load one engine dir outside the env-var path (the
    ``--engine-json <dir>/engine.json`` form of a registry-named
    engine)."""
    with _lock:
        _load_user_dir(Path(engine_dir))


def _import_builtin_templates() -> None:
    from .. import templates

    for m in pkgutil.iter_modules(templates.__path__):
        if m.name.startswith("_"):
            continue
        importlib.import_module(f"{templates.__name__}.{m.name}")


def _load_user_dir(engine_dir: Path) -> None:
    """Import one user engine dir's module (idempotent per resolved
    path).  A broken dir logs and is skipped — one bad entry on the
    path must not take down every `pio-tpu` invocation."""
    try:
        key = str(engine_dir.resolve())
    except OSError:
        key = str(engine_dir)
    if key in _loaded_dirs:
        return
    variant_path = engine_dir / "engine.json"
    if not variant_path.exists():
        logger.warning(
            "%s on %s has no engine.json; skipping", engine_dir,
            ENGINE_PATH_ENV,
        )
        return
    try:
        variant = json.loads(variant_path.read_text())
    except (OSError, ValueError) as e:
        logger.warning("cannot read %s: %s; skipping", variant_path, e)
        return
    module = variant.get("engineModule")
    if not module:
        factory = variant.get("engineFactory", "")
        module = factory.split(".", 1)[0] if factory else "engine"
    candidate = engine_dir / f"{module}.py"
    if not candidate.exists() and not (engine_dir / module).is_dir():
        logger.warning(
            "%s names module %r but %s does not exist; skipping",
            variant_path, module, candidate,
        )
        return
    if key not in sys.path:
        sys.path.insert(0, key)
    # evict a same-named module loaded from a DIFFERENT dir (the
    # cli._engine_dir_on_path contract): user engine dirs all tend to
    # call their module `engine`
    mod = sys.modules.get(module)
    if mod is not None and getattr(mod, "__file__", None) != str(candidate):
        del sys.modules[module]
    prior_source = _spec._current_source
    _spec._current_source = key
    try:
        importlib.import_module(module)
        _loaded_dirs.add(key)
    except Exception:
        logger.exception(
            "engine dir %s failed to import (module %r); skipping",
            engine_dir, module,
        )
    finally:
        _spec._current_source = prior_source
